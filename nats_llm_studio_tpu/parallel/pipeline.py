"""Pipeline parallelism: GPipe-style microbatched inference over a ``pp``
mesh axis.

SURVEY.md §2.2 lists PP as the optional layer-stage sharding (the north-star
configs fit v5e-8 with TP+int8, so PP is a capacity escape hatch, e.g. 70B
bf16 across two hosts). Design, TPU-first:

* the stacked ``[L]`` axis of every ``blocks.*`` leaf and of the KV cache
  shards on ``pp`` — each stage owns ``L/P`` contiguous layers and ONLY its
  slice of weights and cache ever lives on a chip (this is what makes PP a
  capacity tool);
* one ``shard_map`` over pp runs the classic GPipe schedule inside a single
  jit: the batch splits into M microbatches, ``M + P - 1`` ticks scan over
  the pipeline, each tick every stage runs its local layer stack on the
  microbatch currently at its station and hands the activations to the next
  stage via ``lax.ppermute`` over ICI (the reference's NCCL send/recv role,
  compiler-scheduled);
* bubbles (ticks where a stage has no valid microbatch) compute on clamped
  indices and their cache writes are masked out — all shapes static, no
  data-dependent control flow (XLA-friendly).

The final hidden states are psum-broadcast off the last stage and the
norm + lm_head run outside the shard_map, so sampling code is identical to
the dense path. Works for prefill (T > 1, positional KV writes) and for
batched decode (T = 1); the serving ring-decode path stays single-stage —
PP targets capacity, the ring targets latency.

Reference parity: the reference has no tensor plane at all (366 Go LoC of
I/O glue, nats_llm_studio.go); its scale-out is queue-group replication
(README.md:478-484). PP here is the in-tree answer for models that exceed
one replica's HBM.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..models.config import ModelConfig
from ..models.llama import _attention_block, _moe_ffn, lm_head_logits
from ..ops.layers import rms_norm, rope_cos_sin, swiglu
from .mesh import AXIS_PP


def _run_local_stack(x, blocks, cfg: ModelConfig, k_loc, v_loc, start_pos,
                     cos, sin, mask):
    """One stage's layer stack (local ``[Lp]`` slice of blocks/cache) on one
    microbatch. Positional KV writes (ring decode stays single-stage)."""

    def block(carry, inputs):
        x, k_loc, v_loc = carry
        p, layer = inputs
        attn_out, k_loc, v_loc = _attention_block(
            rms_norm(x, p["attn_norm"], cfg.rms_eps, cfg.norm_plus_one),
            p, cfg, k_loc, v_loc, layer, start_pos, cos, sin, mask,
            None, False, None, None, False,
        )
        x = x + attn_out * cfg.residual_scale
        h = rms_norm(x, p["ffn_norm"], cfg.rms_eps, cfg.norm_plus_one)
        ffn = _moe_ffn(h, p, cfg) if cfg.is_moe else swiglu(
            h, p["w_gate"], p["w_up"], p["w_down"], cfg.mlp_act
        )
        x = x + ffn * cfg.residual_scale
        return (x, k_loc, v_loc), None

    l_loc = k_loc.shape[1]
    layer_idx = jnp.arange(l_loc, dtype=jnp.int32)
    (x, k_loc, v_loc), _ = jax.lax.scan(block, (x, k_loc, v_loc),
                                        (blocks, layer_idx))
    return x, k_loc, v_loc


def pipeline_forward(
    params,
    cfg: ModelConfig,
    tokens: jax.Array,  # int32 [B, T]
    k_cache: jax.Array,  # [B, L, Hkv, S, D], L sharded on pp
    v_cache: jax.Array,
    start_pos: jax.Array,  # int32 [B]
    mesh: Mesh,
    n_microbatches: int | None = None,
    logit_positions: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Microbatched pipeline forward; same contract as ``models.llama.forward``
    (positional mode). B must divide by M, L by the pp degree."""
    pp = mesh.shape[AXIS_PP]
    if cfg.n_layers % pp:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by pp={pp}")
    b, t = tokens.shape
    m = n_microbatches or min(pp, b)
    if b % m:
        raise ValueError(f"batch {b} not divisible by {m} microbatches")
    bm = b // m
    dt = jnp.dtype(cfg.dtype)

    # embed + rope tables, replicated (cheap relative to the layer stack)
    x = params["embed"][tokens].astype(dt) * cfg.embedding_scale
    positions = start_pos[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta)
    s_max = k_cache.shape[3]
    key_pos = jnp.arange(s_max, dtype=jnp.int32)
    mask = key_pos[None, None, :] <= positions[:, :, None]  # [B, T, S]

    def mb(a):  # [B, ...] -> [M, Bm, ...]
        return a.reshape(m, bm, *a.shape[1:])

    x_mb, cos_mb, sin_mb = mb(x), mb(cos), mb(sin)
    mask_mb, sp_mb = mb(mask), mb(start_pos)

    pspec = P(AXIS_PP)
    bspec = jax.tree.map(lambda _: P(AXIS_PP), params["blocks"])

    def stage_fn(x_mb, cos_mb, sin_mb, mask_mb, sp_mb, blocks, K, V):
        s = jax.lax.axis_index(AXIS_PP)
        n_ticks = m + pp - 1

        def tick(carry, tck):
            inbuf, K, V, hidden = carry
            mbi = tck - s  # microbatch at my station this tick
            valid = (mbi >= 0) & (mbi < m)
            mbc = jnp.clip(mbi, 0, m - 1)
            # stage 0 injects the fresh microbatch; later stages consume
            # the activations handed over last tick
            x_in = jnp.where(s == 0, x_mb[mbc], inbuf)
            # slice this microbatch's cache rows, run my layers, write the
            # rows back ONLY when the tick is real (bubble writes on the
            # clamped index would corrupt microbatch 0 / m-1). Row slices
            # and gated writes go through tree_map so a quantized KVQ cache
            # (codes + scales) moves as one unit.
            def rows(c):
                return jax.tree.map(
                    lambda a: jax.lax.dynamic_slice_in_dim(a, mbc * bm, bm, axis=0), c
                )

            def write_rows(c, new, old):
                return jax.tree.map(
                    lambda a, n, o: jax.lax.dynamic_update_slice_in_dim(
                        a, jnp.where(valid, n, o), mbc * bm, axis=0
                    ),
                    c, new, old,
                )

            k_rows, v_rows = rows(K), rows(V)
            y, k_new, v_new = _run_local_stack(
                x_in, blocks, cfg, k_rows, v_rows, sp_mb[mbc],
                cos_mb[mbc], sin_mb[mbc], mask_mb[mbc],
            )
            K = write_rows(K, k_new, k_rows)
            V = write_rows(V, v_new, v_rows)
            # the LAST stage's finished microbatch lands in the output
            # buffer; other stages contribute zeros (psum-broadcast below)
            done = valid & (s == pp - 1)
            upd = jnp.where(done, y, jax.lax.dynamic_slice_in_dim(
                hidden, mbc * bm, bm, axis=0))
            hidden = jax.lax.dynamic_update_slice_in_dim(
                hidden, upd, mbc * bm, axis=0
            )
            # hand my activations to the next stage over ICI
            nxt = jax.lax.ppermute(
                y, AXIS_PP, [(i, (i + 1) % pp) for i in range(pp)]
            )
            return (nxt, K, V, hidden), None

        # initial carries must be marked pp-varying (the body's outputs are;
        # shard_map's scan type check rejects the mismatch)
        inbuf0 = jax.lax.pcast(
            jnp.zeros((bm, t, cfg.d_model), dt), (AXIS_PP,), to="varying"
        )
        hidden0 = jax.lax.pcast(
            jnp.zeros((m * bm, t, cfg.d_model), dt), (AXIS_PP,), to="varying"
        )
        (inbuf, K, V, hidden), _ = jax.lax.scan(
            tick, (inbuf0, K, V, hidden0),
            jnp.arange(n_ticks, dtype=jnp.int32),
        )
        # only stage P-1 holds real hidden states; psum broadcasts them
        hidden = jax.lax.psum(
            jnp.where(s == pp - 1, hidden, 0), AXIS_PP
        )
        return hidden, K, V

    # cache layers shard on pp; a quantized KVQ cache carries a spec per
    # leaf (the scale tensor has no trailing head_dim axis)
    from ..ops.kvcache import KVQ, is_quantized

    full = P(None, AXIS_PP, None, None, None)
    cache_pp = (
        KVQ(q=full, s=P(None, AXIS_PP, None, None))
        if is_quantized(k_cache) else full
    )
    hidden, k_cache, v_cache = shard_map(
        stage_fn,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(), bspec, cache_pp, cache_pp),
        out_specs=(P(), cache_pp, cache_pp),
    )(x_mb, cos_mb, sin_mb, mask_mb, sp_mb, params["blocks"], k_cache, v_cache)

    logits = lm_head_logits(params, cfg, hidden, logit_positions, t)
    return logits, k_cache, v_cache

"""Tensor-parallel collective/compute overlap for the decode layer stack.

Under GSPMD, the row-sharded output projections (attention ``wo`` and the
FFN ``w_down``) each end in one blocking all-reduce: the ICI sits idle
while the MXU computes the partial matmul, then the MXU sits idle while
the all-reduce moves d_model bytes — back-to-back, every layer, every
decode step. At decode batch sizes the matmuls are tiny, so the collective
is a large fixed fraction of step latency (the classic Megatron overlap
argument).

``TP_OVERLAP=1`` swaps that single psum for an explicit shard_map ring:
the all-reduce decomposes into 2(tp-1) ``ppermute`` hops over d_model/tp
chunks (reduce-scatter then all-gather), each hop's DMA independent of the
adds on the chunks already in flight — the XLA scheduler interleaves the
sends with the adjacent chunk's compute instead of serializing one bulk
collective after the whole matmul. Decomposed summation also changes the
reduction ORDER, so results differ from the psum path by float rounding
(greedy tokens stay stable in the equivalence tests); the knob therefore
defaults OFF and the GSPMD path stays the bit-reference.

The helpers accept plain arrays, int8 ``QTensor`` and grouped-int4
``QTensor4`` weights: shard_map sees the registered pytrees, so the
per-shard body reuses the exact same ``mm``/``swiglu`` kernels as the
GSPMD path on each shard's slice.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from ..ops.layers import swiglu
from ..ops.wquant import QTensor, QTensor4, mm
from .mesh import AXIS_TP

__all__ = [
    "tp_overlap_enabled",
    "ring_all_reduce",
    "overlap_row_proj",
    "overlap_ffn",
]


def tp_overlap_enabled() -> bool:
    """TP_OVERLAP=1 turns on the ppermute ring for decode projections."""
    return os.environ.get("TP_OVERLAP", "0").strip().lower() in ("1", "true", "on")


def _tp(mesh) -> int:
    if mesh is None:
        return 1
    return mesh.shape.get(AXIS_TP, 1)


def ring_all_reduce(y: jax.Array, axis_name: str, tp: int) -> jax.Array:
    """All-reduce ``y`` over ``axis_name`` as a reduce-scatter/all-gather
    ppermute ring (must run inside shard_map). The last axis splits into
    ``tp`` chunks; each of the 2(tp-1) hops moves one chunk while the adds
    on the previously-received chunk proceed. Falls back to psum when the
    last axis does not split."""
    if tp <= 1:
        return y
    d = y.shape[-1]
    if d % tp:
        return jax.lax.psum(y, axis_name)
    c = d // tp
    idx = jax.lax.axis_index(axis_name)
    fwd = [(j, (j + 1) % tp) for j in range(tp)]
    chunks = y.reshape(*y.shape[:-1], tp, c)
    ax = chunks.ndim - 2

    def chunk(j):
        return jax.lax.dynamic_index_in_dim(chunks, j % tp, axis=ax,
                                            keepdims=False)

    # reduce-scatter: start one chunk "ahead"; each hop delivers the
    # running partial for the chunk this shard adds its local copy to.
    # After tp-1 hops shard idx owns the FULL sum of chunk (idx+2) % tp.
    acc = chunk(idx + 1)
    for s in range(1, tp):
        acc = jax.lax.ppermute(acc, axis_name, fwd)
        acc = acc + chunk(idx + 1 - s)

    # all-gather: circulate the owned chunks back around the same ring
    out = jnp.zeros_like(chunks)

    def put(buf, j, val):
        return jax.lax.dynamic_update_index_in_dim(buf, val, j % tp, axis=ax)

    out = put(out, idx + 2, acc)
    cur = acc
    for h in range(1, tp):
        cur = jax.lax.ppermute(cur, axis_name, fwd)
        out = put(out, idx + 2 - h, cur)
    return out.reshape(y.shape)


def _weight_specs(w, row_sharded: bool):
    """The shard_map in_spec pytree for one projection weight.

    row_sharded: contraction axis on tp (wo / w_down — the overlap
    targets); else column-sharded (w_gate / w_up). int8 QTensor scales are
    extent-1 on the contraction axis, so they never shard on it; grouped
    QTensor4 scales/zeros shard exactly as the codes (see shard_params)."""
    wspec = P(AXIS_TP, None) if row_sharded else P(None, AXIS_TP)
    if isinstance(w, QTensor):
        return QTensor(q=wspec, s=P(None, None) if row_sharded else wspec)
    if isinstance(w, QTensor4):
        return QTensor4(q=wspec, s=wspec, z=wspec, group=w.group)
    return wspec


def overlap_row_proj(x: jax.Array, w, mesh) -> jax.Array:
    """``x @ w`` for a row-sharded (contraction on tp) projection with the
    trailing all-reduce done as the ppermute ring. ``x``'s last axis must
    carry the matching tp sharding (the attention heads fold) — the
    per-shard slice feeds the local matmul directly."""
    tp = _tp(mesh)
    if tp <= 1:
        return mm(x, w)
    xspec = P(*([None] * (x.ndim - 1) + [AXIS_TP]))

    def f(xs, ws):
        return ring_all_reduce(mm(xs, ws), AXIS_TP, tp)

    return shard_map(
        f, mesh=mesh,
        in_specs=(xspec, _weight_specs(w, row_sharded=True)),
        out_specs=P(*([None] * x.ndim)), check_rep=False,
    )(x, w)


def overlap_ffn(h: jax.Array, w_gate, w_up, w_down, act: str, mesh) -> jax.Array:
    """The whole SwiGLU FFN in one shard_map: gate/up column shards feed
    the row-sharded down projection without rematerializing the [.., ff]
    intermediate across shards, and the down matmul's all-reduce rides the
    ppermute ring. ``h`` is replicated (the layer input after the attention
    all-reduce)."""
    tp = _tp(mesh)
    if tp <= 1:
        return swiglu(h, w_gate, w_up, w_down, act)
    hspec = P(*([None] * h.ndim))

    def f(hs, wg, wu, wd):
        return ring_all_reduce(swiglu(hs, wg, wu, wd, act), AXIS_TP, tp)

    return shard_map(
        f, mesh=mesh,
        in_specs=(
            hspec,
            _weight_specs(w_gate, row_sharded=False),
            _weight_specs(w_up, row_sharded=False),
            _weight_specs(w_down, row_sharded=True),
        ),
        out_specs=hspec, check_rep=False,
    )(h, w_gate, w_up, w_down)

"""Streaming sharded weight loading.

SURVEY.md §7 hard part #2: a 70B GGUF is ~40 GB on disk and ~140 GB as bf16 —
materializing the full pytree on host before sharding (models/llama.py's
``load_params_from_gguf``) cannot work there. This loader walks the tensor
index one entry at a time: mmap read -> dequant (native C++ path) -> cast ->
``jax.device_put`` with the tensor's NamedSharding -> host buffer released,
so peak host memory is one tensor, not one model. Stacked [L]-leading leaves
are assembled on device layer-by-layer via per-layer placement and
``jax.lax`` concatenation-free stacking (device_put per layer slice into the
stacked sharding).
"""

from __future__ import annotations

import gc
import logging
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.config import ModelConfig
from ..models.llama import _rope_deinterleave
from .sharding import param_sharding_rules

log = logging.getLogger(__name__)


def _layer_sharding(mesh: Mesh, spec: P) -> NamedSharding:
    """Sharding for one [L]-slice of a stacked leaf (drop the L axis rule)."""
    return NamedSharding(mesh, P(*spec[1:]))


def _place(arr: np.ndarray, mesh: Mesh, spec: P, dtype) -> jax.Array:
    return jax.device_put(jnp.asarray(arr, dtype), NamedSharding(mesh, spec))


def load_params_sharded(
    reader, cfg: ModelConfig, mesh: Mesh, dtype: str | None = None
) -> dict[str, Any]:
    """Build the stacked-params pytree directly on the mesh, one tensor at a
    time. Same tensor-name contract as models.llama.load_params_from_gguf."""
    dt = jnp.dtype(dtype or cfg.dtype)
    rules = param_sharding_rules(mesh)

    def t(name: str) -> np.ndarray:
        return reader.tensor(name).to_numpy()

    def mat(name: str) -> np.ndarray:
        return np.ascontiguousarray(t(name).T)

    params: dict[str, Any] = {
        "embed": _place(t("token_embd.weight"), mesh, rules["embed"], dt),
        "out_norm": _place(t("output_norm.weight"), mesh, rules["out_norm"], dt),
    }
    if "output.weight" in reader.tensors:
        params["lm_head"] = _place(mat("output.weight"), mesh, rules["lm_head"], dt)

    # stacked per-layer leaves: place each layer slice with the slice
    # sharding, then stack on-device (jnp.stack of committed sharded arrays
    # stays on device; the host copy of each slice dies right after placement)
    per_layer: dict[str, list[jax.Array]] = {}

    def push(key: str, arr: np.ndarray) -> None:
        spec = rules[f"blocks.{key}"]
        sh = _layer_sharding(mesh, spec)
        per_layer.setdefault(key, []).append(jax.device_put(jnp.asarray(arr, dt), sh))

    for i in range(cfg.n_layers):
        pre = f"blk.{i}"
        push("attn_norm", t(f"{pre}.attn_norm.weight"))
        push("ffn_norm", t(f"{pre}.ffn_norm.weight"))
        push("wq", _rope_deinterleave(mat(f"{pre}.attn_q.weight"), cfg.n_heads, cfg.head_dim))
        push("wk", _rope_deinterleave(mat(f"{pre}.attn_k.weight"), cfg.n_kv_heads, cfg.head_dim))
        push("wv", mat(f"{pre}.attn_v.weight"))
        push("wo", mat(f"{pre}.attn_output.weight"))
        if cfg.is_moe:
            push("router", mat(f"{pre}.ffn_gate_inp.weight"))
            push("w_gate_e", t(f"{pre}.ffn_gate_exps.weight").transpose(0, 2, 1))
            push("w_up_e", t(f"{pre}.ffn_up_exps.weight").transpose(0, 2, 1))
            push("w_down_e", t(f"{pre}.ffn_down_exps.weight").transpose(0, 2, 1))
        else:
            push("w_gate", mat(f"{pre}.ffn_gate.weight"))
            push("w_up", mat(f"{pre}.ffn_up.weight"))
            push("w_down", mat(f"{pre}.ffn_down.weight"))
        if i % 8 == 7:
            gc.collect()  # drop dequant temporaries promptly on big models

    blocks: dict[str, jax.Array] = {}
    for key, slices in per_layer.items():
        spec = rules[f"blocks.{key}"]
        stacked = jnp.stack(slices)
        blocks[key] = jax.device_put(stacked, NamedSharding(mesh, spec))
    params["blocks"] = blocks
    return params

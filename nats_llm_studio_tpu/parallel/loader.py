"""Streaming sharded weight loading.

SURVEY.md §7 hard part #2: a 70B GGUF is ~40 GB on disk and ~140 GB as bf16 —
materializing the full pytree on host before sharding (models/llama.py's
``load_params_from_gguf``) cannot work there. This loader walks the tensor
index one entry at a time: mmap read -> dequant (native C++ path) -> cast ->
``jax.device_put`` with the tensor's NamedSharding -> host buffer released,
so peak host memory is one tensor, not one model. Stacked [L]-leading leaves
are assembled on device layer-by-layer via per-layer placement and
``jax.lax`` concatenation-free stacking (device_put per layer slice into the
stacked sharding).
"""

from __future__ import annotations

import gc
import logging
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.config import ModelConfig
from ..models.llama import _rope_deinterleave
from ..ops.wquant import (
    QTensor,
    QTensor4,
    quantizable,
    quantize_weight,
    quantize_weight4,
)
from .sharding import param_sharding_rules, scale_spec

log = logging.getLogger(__name__)


def _layer_sharding(mesh: Mesh, spec: P) -> NamedSharding:
    """Sharding for one [L]-slice of a stacked leaf (drop the L axis rule)."""
    return NamedSharding(mesh, P(*spec[1:]))


def _place(arr: np.ndarray, mesh: Mesh, spec: P, dtype) -> jax.Array:
    return jax.device_put(jnp.asarray(arr, dtype), NamedSharding(mesh, spec))


def load_params_sharded(
    reader, cfg: ModelConfig, mesh: Mesh, dtype: str | None = None,
    quant: str = "none", group: int = 32,
) -> dict[str, Any]:
    """Build the stacked-params pytree directly on the mesh, one tensor at a
    time. Same tensor-name contract as models.llama.load_params_from_gguf.

    ``quant="int8"`` re-quantizes each matmul weight to symmetric
    per-output-channel int8 on the host *before* placement, so device HBM
    holds int8 + scales — the path that fits Llama-3-70B on a v5e-8
    (BASELINE.md config 3) and halves decode weight traffic. ``quant="int4"``
    goes further: asymmetric grouped QTensor4 (``group`` rows per
    scale/zero-point), ~4.3 bits/weight, halving traffic again.
    """
    dt = jnp.dtype(dtype or cfg.dtype)
    if quant not in ("none", "int8", "int4"):
        raise ValueError(f"unknown quant mode {quant!r}")
    rules = param_sharding_rules(mesh, cfg)

    def t(name: str) -> np.ndarray:
        return reader.tensor(name).to_numpy()

    def mat(name: str) -> np.ndarray:
        return np.ascontiguousarray(t(name).T)

    def place_leaf(key: str, arr: np.ndarray, spec: P, layered: bool):
        """Host tensor -> device leaf (bf16 array or int8/int4 QTensor)."""
        w_sh = _layer_sharding(mesh, spec) if layered else NamedSharding(mesh, spec)
        if quant == "int8" and quantizable(key):
            qt = quantize_weight(arr)
            s_spec = scale_spec(P(*spec[1:])) if layered else scale_spec(spec)
            return QTensor(
                q=jax.device_put(jnp.asarray(qt.q), w_sh),
                s=jax.device_put(jnp.asarray(qt.s), NamedSharding(mesh, s_spec)),
            )
        if quant == "int4" and quantizable(key):
            # codes AND grouped scales/zeros all keep the weight's spec
            # (see shard_params: the grouped axis shards with the
            # contraction axis, it is not extent-1 like the int8 scale)
            qt = quantize_weight4(arr, group=group)
            return QTensor4(
                q=jax.device_put(jnp.asarray(qt.q), w_sh),
                s=jax.device_put(jnp.asarray(qt.s), w_sh),
                z=jax.device_put(jnp.asarray(qt.z), w_sh),
                group=qt.group,
            )
        return jax.device_put(jnp.asarray(arr, dt), w_sh)

    params: dict[str, Any] = {
        "embed": _place(t("token_embd.weight"), mesh, rules["embed"], dt),
        "out_norm": _place(t("output_norm.weight"), mesh, rules["out_norm"], dt),
    }
    if "output.weight" in reader.tensors:
        params["lm_head"] = place_leaf(
            "lm_head", mat("output.weight"), rules["lm_head"], layered=False
        )
    else:
        # tied embeddings: materialize the [d, vocab] head now (contiguous,
        # shardable, quantizable) instead of transposing embed every step
        params["lm_head"] = place_leaf(
            "lm_head", np.ascontiguousarray(t("token_embd.weight").T),
            rules["lm_head"], layered=False,
        )

    # stacked per-layer leaves: place each layer slice with the slice
    # sharding, then stack on-device (jnp.stack of committed sharded arrays
    # stays on device; the host copy of each slice dies right after placement)
    per_layer: dict[str, list] = {}

    def push(key: str, arr: np.ndarray) -> None:
        spec = rules[f"blocks.{key}"]
        per_layer.setdefault(key, []).append(place_leaf(key, arr, spec, layered=True))

    for i in range(cfg.n_layers):
        pre = f"blk.{i}"
        push("attn_norm", t(f"{pre}.attn_norm.weight"))
        push("ffn_norm", t(f"{pre}.ffn_norm.weight"))
        push("wq", _rope_deinterleave(mat(f"{pre}.attn_q.weight"), cfg.n_heads, cfg.head_dim))
        push("wk", _rope_deinterleave(mat(f"{pre}.attn_k.weight"), cfg.n_kv_heads, cfg.head_dim))
        push("wv", mat(f"{pre}.attn_v.weight"))
        push("wo", mat(f"{pre}.attn_output.weight"))
        if cfg.attn_bias:
            push("bq", _rope_deinterleave(
                t(f"{pre}.attn_q.bias")[None], cfg.n_heads, cfg.head_dim)[0])
            push("bk", _rope_deinterleave(
                t(f"{pre}.attn_k.bias")[None], cfg.n_kv_heads, cfg.head_dim)[0])
            push("bv", t(f"{pre}.attn_v.bias"))
        if cfg.is_moe:
            push("router", mat(f"{pre}.ffn_gate_inp.weight"))
            push("w_gate_e", t(f"{pre}.ffn_gate_exps.weight").transpose(0, 2, 1))
            push("w_up_e", t(f"{pre}.ffn_up_exps.weight").transpose(0, 2, 1))
            push("w_down_e", t(f"{pre}.ffn_down_exps.weight").transpose(0, 2, 1))
        else:
            push("w_gate", mat(f"{pre}.ffn_gate.weight"))
            push("w_up", mat(f"{pre}.ffn_up.weight"))
            push("w_down", mat(f"{pre}.ffn_down.weight"))
        if i % 8 == 7:
            gc.collect()  # drop dequant temporaries promptly on big models

    blocks: dict[str, Any] = {}
    for key, slices in per_layer.items():
        spec = rules[f"blocks.{key}"]
        if isinstance(slices[0], QTensor):
            blocks[key] = QTensor(
                q=jax.device_put(jnp.stack([s.q for s in slices]),
                                 NamedSharding(mesh, spec)),
                s=jax.device_put(jnp.stack([s.s for s in slices]),
                                 NamedSharding(mesh, scale_spec(spec))),
            )
        elif isinstance(slices[0], QTensor4):
            sh = NamedSharding(mesh, spec)
            blocks[key] = QTensor4(
                q=jax.device_put(jnp.stack([s.q for s in slices]), sh),
                s=jax.device_put(jnp.stack([s.s for s in slices]), sh),
                z=jax.device_put(jnp.stack([s.z for s in slices]), sh),
                group=slices[0].group,
            )
        else:
            blocks[key] = jax.device_put(jnp.stack(slices), NamedSharding(mesh, spec))
    params["blocks"] = blocks
    return params

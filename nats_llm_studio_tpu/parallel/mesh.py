"""Mesh construction from a spec string (env var ``TPU_MESH``).

Axis vocabulary: ``dp`` (data/batch), ``pp`` (pipeline: layer stages), ``tp``
(tensor: heads + MLP), ``ep`` (experts), ``sp`` (sequence/context — ring
attention). A spec is ``"tp=8"`` or ``"dp=2,tp=4"`` — the compact named-axis
grammar ``"dp2,ep2,tp2"`` is accepted as the same thing; ``"auto"``/empty
uses all local devices on tp.

Multi-axis serving (the dp/ep/sp axes in the live serving path): ``dp``
splits the mesh into independent batcher replicas (``dp_submeshes``), ``ep``
shards MoE expert stacks, ``sp`` enables ring-attention sequence-parallel
prefill for long prompts (``RING_PREFILL_MIN_TOKENS``).

Multi-host: when ``jax.distributed.initialize`` has run, ``jax.devices()``
spans hosts and the same specs build DCN-crossing meshes; keep dp outermost
so its collectives ride DCN and tp's ride ICI (devices are enumerated
host-major).
"""

from __future__ import annotations

import jax
from jax.experimental import mesh_utils
from jax.sharding import Mesh

AXIS_DP = "dp"
AXIS_PP = "pp"
AXIS_TP = "tp"
AXIS_EP = "ep"
AXIS_SP = "sp"
# construction order: dp outermost (DCN-friendly), then pipeline stages,
# then the intra-stage axes
_KNOWN = (AXIS_DP, AXIS_PP, AXIS_EP, AXIS_SP, AXIS_TP)


def parse_mesh_spec(spec: str) -> dict[str, int]:
    """``"dp=2,tp=4"`` -> {"dp": 2, "tp": 4} (order normalized dp,ep,sp,tp).

    The compact named-axis grammar ``"dp2,ep2,tp2"`` (no ``=``) parses to
    the same dict — the axis name is the leading alpha run, the factor the
    trailing digits."""
    spec = (spec or "").strip().lower()
    if spec in ("", "auto"):
        return {}
    out: dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        name, eq, val = part.partition("=")
        if not eq:
            # compact grammar: "dp2" / "tp8" — split at the first digit
            i = 0
            while i < len(part) and not part[i].isdigit():
                i += 1
            name, val = part[:i], part[i:]
        if name not in _KNOWN:
            raise ValueError(f"unknown mesh axis {name!r} (known: {_KNOWN})")
        n = int(val)
        if n <= 0:
            raise ValueError(f"mesh axis {name}={n} must be positive")
        out[name] = n
    return {k: out[k] for k in _KNOWN if k in out}


def build_mesh(spec: str | dict[str, int] = "", devices=None) -> Mesh:
    """Build a Mesh from a spec; validates the axis product against the
    device count. Empty/"auto" puts every device on the tp axis."""
    devices = list(devices if devices is not None else jax.devices())
    axes = parse_mesh_spec(spec) if isinstance(spec, str) else dict(spec)
    if not axes:
        axes = {AXIS_TP: len(devices)}
    n = 1
    for v in axes.values():
        n *= v
    if n != len(devices):
        raise ValueError(f"mesh {axes} needs {n} devices, have {len(devices)}")
    arr = mesh_utils.create_device_mesh(tuple(axes.values()), devices=devices)
    return Mesh(arr, tuple(axes.keys()))


# spellings that force unsharded (tp=1) serving regardless of device count
_MESH_OFF = ("off", "none", "0", "1", "tp=1", "tp1")


def serving_mesh(spec: str = "auto", devices=None) -> Mesh | None:
    """The serving path's mesh (env knob ``MESH_SHAPE``). Empty/``auto``
    puts every local device on tp — tensor-parallel serving is the
    multi-device default — and returns None on a single-device host, where
    the unsharded code path *is* tp=1 and a one-device Mesh would only add
    partitioner overhead. ``off``/``none``/``1``/``tp=1`` force unsharded
    serving on any host; explicit specs ("tp=4", "dp=2,tp=4") build
    exactly that mesh on the first axis-product devices (so "tp=2" on an
    8-chip host serves on 2 chips instead of erroring)."""
    s = (spec or "").strip().lower()
    if s in _MESH_OFF:
        return None
    devices = list(devices if devices is not None else jax.devices())
    if s in ("", "auto"):
        return None if len(devices) == 1 else build_mesh("", devices=devices)
    axes = parse_mesh_spec(spec)
    n = 1
    for v in axes.values():
        n *= v
    # an oversized spec keeps the full list so build_mesh raises its clear
    # "needs N devices, have M" error
    return build_mesh(axes, devices=devices[:n] if n <= len(devices) else devices)


def dp_submeshes(mesh: Mesh | None) -> list[Mesh | None]:
    """Split a mesh with a dp axis into one submesh per dp slice.

    The serving stack runs dp as independent batcher REPLICAS, not as a
    batch-sharded axis inside one jit grid: each replica owns a disjoint
    device slice (the dp axis is outermost, so slices are contiguous and
    DCN-friendly) with the remaining (ep, sp, tp) axes intact, its own
    slot table, KV pool, and jit grid. Weights are loaded once on host and
    placed per slice — replicated ALONG dp, sharded WITHIN each slice — so
    per-chip weight bytes match a single-replica mesh of the slice shape.

    A mesh without dp (or ``None``) returns ``[mesh]`` unchanged. A pure-dp
    mesh (``"dp=2"``) yields single-device submeshes carrying a size-1 tp
    axis, which serves exactly like the unsharded path.
    """
    if mesh is None or mesh.shape.get(AXIS_DP, 1) <= 1:
        return [mesh]
    import numpy as np

    names = list(mesh.axis_names)
    i = names.index(AXIS_DP)
    rest = tuple(n for n in names if n != AXIS_DP)
    out: list[Mesh | None] = []
    for k in range(mesh.shape[AXIS_DP]):
        # np.take collapses a 1-D (pure-dp) device grid to a bare Device
        arr = np.asarray(np.take(mesh.devices, k, axis=i))
        if not rest:
            out.append(Mesh(arr.reshape((1,)), (AXIS_TP,)))
        else:
            out.append(Mesh(arr, rest))
    return out

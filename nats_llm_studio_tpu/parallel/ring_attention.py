"""Ring attention: causal attention with the sequence axis sharded over the
``sp`` mesh axis.

Long-context prefill where one chip cannot hold the whole [T, T] interaction:
each device keeps its local Q/K/V sequence chunk; K/V chunks rotate around
the ring via ``ppermute`` (one ICI hop per step) while each device folds the
incoming block into a running online-softmax state — compute and transfer
overlap, memory stays O(T/n per chip). The reference has no analog (context
length is whatever external llama.cpp supports — SURVEY.md §5 long-context);
this is the TPU-native design the KV layout [B, L, Hkv, S, D] was chosen for:
adding the sp axis shards S without relayout.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # moved out of experimental in newer JAX
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from .mesh import AXIS_SP

_NEG_INF = -1e30

# serving gate: prompts below this many tokens prefill on the dense/flash
# path even when the mesh has an sp axis — ring rotation latency only pays
# for itself once the [T, T] interaction stops fitting one chip's lane
_RING_PREFILL_MIN_DEFAULT = 4096


def ring_prefill_min_tokens(default: int = _RING_PREFILL_MIN_DEFAULT) -> int:
    """Token threshold (env ``RING_PREFILL_MIN_TOKENS``) above which fresh
    prefill routes through :func:`ring_attention` on an sp>1 mesh. Read at
    trace time — each prefill bucket's program bakes its own decision, so
    one serving grid mixes dense short-prompt and ring long-prompt
    programs."""
    import os

    try:
        return int(os.environ.get("RING_PREFILL_MIN_TOKENS", default))
    except ValueError:
        return default


def use_ring_prefill(mesh: Mesh | None, t: int) -> bool:
    """Should a fresh prefill of ``t`` tokens take the ring path on this
    mesh? Requires an sp axis > 1, the threshold, and sp | t (shard_map
    needs equal sequence chunks)."""
    if mesh is None or t <= 1:
        return False
    sp = mesh.shape.get(AXIS_SP, 1) if AXIS_SP in mesh.axis_names else 1
    return sp > 1 and t >= ring_prefill_min_tokens() and t % sp == 0


def _block_attn(q, k, v, mask, scale):
    """One K/V block folded into online-softmax partials.

    q: [B, Tq, Hq, D]; k, v: [B, Tk, Hkv, D]; mask: [Tq, Tk] bool.
    Returns (acc [B, Hkv, G, Tq, D] f32 unnormalized, m, l [B, Hkv, G, Tq]).
    """
    b, tq, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    qg = q.reshape(b, tq, hkv, g, d)
    s = jnp.einsum("bthgd,bshd->bhgts", qg, k, preferred_element_type=jnp.float32) * scale
    s = jnp.where(mask[None, None, None, :, :], s, _NEG_INF)
    m = jnp.max(s, axis=-1)
    # explicit zero for masked entries: when a row is fully masked m == NEG_INF
    # and exp(s - m) would be exp(0) = 1 there
    p = jnp.where(mask[None, None, None, :, :], jnp.exp(s - m[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhgts,bshd->bhgtd", p, v.astype(jnp.float32))
    return acc, m, l


def _merge(acc1, m1, l1, acc2, m2, l2):
    m = jnp.maximum(m1, m2)
    c1 = jnp.exp(m1 - m)
    c2 = jnp.exp(m2 - m)
    return acc1 * c1[..., None] + acc2 * c2[..., None], m, l1 * c1 + l2 * c2


def ring_attention(
    q: jax.Array,  # [B, T, Hq, D] — T sharded on sp
    k: jax.Array,  # [B, T, Hkv, D]
    v: jax.Array,
    scale: float,
    mesh: Mesh,
    axis: str = AXIS_SP,
) -> jax.Array:
    """Causal attention with T sharded over ``axis``. Returns [B, T, Hq, D]
    in q.dtype, sharded like q."""
    n = mesh.shape[axis]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def local(q, k, v):
        b, tq, hq, d = q.shape
        hkv = k.shape[2]
        g = hq // hkv
        idx = jax.lax.axis_index(axis)
        q_pos = idx * tq + jnp.arange(tq)

        def step(s, carry):
            acc, m, l, kc, vc = carry
            src = (idx - s) % n
            k_pos = src * tq + jnp.arange(tq)
            mask = k_pos[None, :] <= q_pos[:, None]
            acc_b, m_b, l_b = _block_attn(q, kc, vc, mask, scale)
            acc, m, l = _merge(acc, m, l, acc_b, m_b, l_b)
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)
            return acc, m, l, kc, vc

        # mark the zero-init carry as device-varying over the ring axis so
        # the scan carry type matches its (varying) outputs. The marker has
        # moved across JAX versions (pcast -> pvary) and older releases
        # (<= 0.4.x) have neither — there the varying-axes type system does
        # not exist and the plain carry is already correct
        _pcast = getattr(jax.lax, "pcast", None)
        _pvary = getattr(jax.lax, "pvary", None)
        if _pcast is not None:
            vary = lambda x: _pcast(x, (axis,), to="varying")
        elif _pvary is not None:
            vary = lambda x: _pvary(x, (axis,))
        else:
            vary = lambda x: x
        acc0 = vary(jnp.zeros((b, hkv, g, tq, d), jnp.float32))
        m0 = vary(jnp.full((b, hkv, g, tq), _NEG_INF, jnp.float32))
        l0 = vary(jnp.zeros((b, hkv, g, tq), jnp.float32))
        acc, m, l, _, _ = jax.lax.fori_loop(0, n, step, (acc0, m0, l0, k, v))
        out = acc / jnp.maximum(l, 1e-30)[..., None]  # [B, Hkv, G, Tq, D]
        return out.transpose(0, 3, 1, 2, 4).reshape(b, tq, hq, d).astype(q.dtype)

    seq = P(None, axis, None, None)
    fn = shard_map(local, mesh=mesh, in_specs=(seq, seq, seq), out_specs=seq)
    return fn(q, k, v)


def ring_attention_sharded(q, k, v, scale: float, mesh: Mesh) -> jax.Array:
    """Convenience: sp ring when the mesh has an sp axis > 1, dense otherwise."""
    if AXIS_SP in mesh.axis_names and mesh.shape[AXIS_SP] > 1:
        return ring_attention(q, k, v, scale, mesh)
    from ..ops.layers import gqa_attention

    t = q.shape[1]
    pos = jnp.arange(t)
    mask = jnp.broadcast_to(pos[None, :] <= pos[:, None], (q.shape[0], t, t))
    return gqa_attention(q, k, v, mask, scale)

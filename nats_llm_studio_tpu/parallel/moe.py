"""Routed (sparse) MoE dispatch — the expert-parallel path.

The dense-dispatch form in models/llama.py computes every expert for every
token (E/k x wasted FLOPs — Mixtral top-2-of-8 does 4x extra work,
VERDICT.md missing #3). This module routes instead: each token's hidden
state is scattered into per-expert slot buffers of *static* capacity, each
expert runs one batched SwiGLU over its slots, and results gather back with
the routing weights. All shapes are static (XLA-friendly); token->slot
movement is scatter/gather (O(N*k*D)), not the one-hot-matmul dispatch whose
FLOPs explode at prefill token counts.

Expert parallelism: under ``shard_map`` over the mesh's ``ep`` axis each
shard owns E/P experts (weights arrive pre-sharded by
``sharding.param_sharding_rules``), scatters the replicated tokens into its
local slots, computes, and ``psum``s the combined output — the all-to-all of
the reference's NCCL-style EP expressed as XLA collectives over ICI
(SURVEY.md §7 hard part #4).

Capacity: C = ceil(cf * k * N / E). Tokens overflowing an expert's C slots
drop that expert's contribution (standard capacity-factor semantics; the
routing weight mass is not renormalized). cf defaults high enough that
drops require pathological routing skew.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..models.config import ModelConfig
from ..ops.wquant import q_einsum
from .mesh import AXIS_EP


def _capacity(n_tokens: int, cfg: ModelConfig, capacity_factor: float) -> int:
    per_expert = capacity_factor * cfg.n_experts_used * n_tokens / cfg.n_experts
    return max(1, math.ceil(per_expert))


def _route(xf: jax.Array, router, cfg: ModelConfig, capacity: int):
    """Top-k routing + slot assignment. Returns (top_w [N,k] f32,
    slot [N,k] int32 — global slot id e*C + position, or the trash slot
    E*C for capacity overflow)."""
    n = xf.shape[0]
    e, k = cfg.n_experts, cfg.n_experts_used
    router_logits = q_einsum("nd,df->nf", xf, router).astype(jnp.float32)
    top_w, top_idx = jax.lax.top_k(router_logits, k)  # [N,k]
    top_w = jax.nn.softmax(top_w, axis=-1)

    # position of assignment (n, j) within its expert, in (n-major, j-minor)
    # order: running count of prior assignments to the same expert
    flat_e = top_idx.reshape(-1)  # [N*k]
    onehot = jax.nn.one_hot(flat_e, e, dtype=jnp.int32)  # [N*k, E]
    pos = (jnp.cumsum(onehot, axis=0) * onehot).sum(-1) - 1  # [N*k]
    slot = jnp.where(pos < capacity, flat_e * capacity + pos, e * capacity)
    return top_w, slot.reshape(n, k).astype(jnp.int32)


def _expert_swiglu(xe: jax.Array, w_gate, w_up, w_down) -> jax.Array:
    """Batched per-expert SwiGLU. xe: [E_local, C, D]."""
    gate = jax.nn.silu(q_einsum("ecd,edf->ecf", xe, w_gate))
    up = q_einsum("ecd,edf->ecf", xe, w_up)
    return q_einsum("ecf,efd->ecd", gate * up, w_down)


def routed_moe_ffn(
    x: jax.Array,  # [B, T, D]
    p: dict,  # router / w_gate_e / w_up_e / w_down_e (arrays or QTensor)
    cfg: ModelConfig,
    mesh: Mesh | None = None,
    capacity_factor: float = 2.0,
) -> jax.Array:
    """Sparse top-k MoE FFN; expert-parallel when ``mesh`` has an ep axis."""
    b, t, d = x.shape
    n = b * t
    e, k = cfg.n_experts, cfg.n_experts_used
    cap = _capacity(n, cfg, capacity_factor)
    xf = x.reshape(n, d)
    top_w, slot = _route(xf, p["router"], cfg, cap)
    top_w = top_w.astype(x.dtype)

    ep = mesh.shape.get(AXIS_EP, 1) if mesh is not None else 1
    if ep <= 1:
        # single-shard: one global slot buffer (+1 trash row for drops)
        buf = jnp.zeros((e * cap + 1, d), x.dtype)
        buf = buf.at[slot.reshape(-1)].set(
            jnp.repeat(xf, k, axis=0), mode="drop", unique_indices=True
        )
        ye = _expert_swiglu(
            buf[: e * cap].reshape(e, cap, d), p["w_gate_e"], p["w_up_e"], p["w_down_e"]
        ).reshape(e * cap, d)
        ye = jnp.concatenate([ye, jnp.zeros((1, d), ye.dtype)])  # trash row -> 0
        picked = ye[slot.reshape(-1)].reshape(n, k, d)
        out = jnp.einsum("nkd,nk->nd", picked, top_w)
        return out.reshape(b, t, d)

    e_local = e // ep
    espec = P(AXIS_EP, None, None)

    def shard_fn(xf, top_w, slot, w_gate, w_up, w_down):
        # xf/top_w/slot replicated; expert weights sharded on ep (leading E)
        shard = jax.lax.axis_index(AXIS_EP)
        lo = shard * e_local * cap
        local = slot - lo  # [N,k] local slot id
        # out-of-shard or trash assignments -> local trash row
        local = jnp.where((local >= 0) & (local < e_local * cap), local, e_local * cap)
        buf = jnp.zeros((e_local * cap + 1, d), xf.dtype)
        buf = buf.at[local.reshape(-1)].set(
            jnp.repeat(xf, k, axis=0), mode="drop", unique_indices=True
        )
        ye = _expert_swiglu(
            buf[: e_local * cap].reshape(e_local, cap, d), w_gate, w_up, w_down
        ).reshape(e_local * cap, d)
        ye = jnp.concatenate([ye, jnp.zeros((1, d), ye.dtype)])
        picked = ye[local.reshape(-1)].reshape(n, k, d)
        part = jnp.einsum("nkd,nk->nd", picked, top_w)
        return jax.lax.psum(part, AXIS_EP)

    out = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(), P(), P(), espec, espec, espec),
        out_specs=P(),
    )(xf, top_w, slot, p["w_gate_e"], p["w_up_e"], p["w_down_e"])
    return out.reshape(b, t, d)

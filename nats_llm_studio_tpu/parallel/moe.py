"""Routed (sparse) MoE dispatch — the expert-parallel path.

The dense-dispatch form in models/llama.py computes every expert for every
token (E/k x wasted FLOPs — Mixtral top-2-of-8 does 4x extra work,
VERDICT.md missing #3). This module routes instead: each token's hidden
state is scattered into per-expert slot buffers of *static* capacity, each
expert runs one batched SwiGLU over its slots, and results gather back with
the routing weights. All shapes are static (XLA-friendly); token->slot
movement is scatter/gather (O(N*k*D)), not the one-hot-matmul dispatch whose
FLOPs explode at prefill token counts.

Expert parallelism (SURVEY.md §7 hard part #4) is a true ALL-TO-ALL over
the mesh's ``ep`` axis: tokens are sharded on ep, each shard routes its
N/ep tokens locally, exchanges only the assigned slot payloads
(``lax.all_to_all`` of [ep, E_local*C_pair, D] — per-shard bytes scale
with cf*k*N/ep*D, NOT with N*D like a replicate+psum), computes its own
E/ep experts over slots from every source, and a second all_to_all returns
the outputs for a local weighted combine. Expert weights arrive
pre-sharded on ep by ``sharding.param_sharding_rules``.

Capacity: per (source shard, expert) pair C_pair = ceil(cf * k * (N/ep)/E)
slots (total per-expert capacity ep*C_pair). Tokens overflowing their
pair's slots drop that expert's contribution (standard capacity-factor
semantics; the routing weight mass is not renormalized). cf defaults high
enough that drops require pathological routing skew.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

try:  # moved out of experimental in newer JAX
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..models.config import ModelConfig
from ..ops.wquant import q_einsum
from .mesh import AXIS_EP


def _capacity(n_tokens: int, cfg: ModelConfig, capacity_factor: float) -> int:
    per_expert = capacity_factor * cfg.n_experts_used * n_tokens / cfg.n_experts
    return max(1, math.ceil(per_expert))


def _route(xf: jax.Array, router, cfg: ModelConfig, capacity: int):
    """Top-k routing + slot assignment over the tokens GIVEN (the whole
    batch on a single shard; one shard's local block under EP — inside a
    shard the id g*C + pos is exactly the local all-to-all send-buffer
    layout dst*(E_local*C) + le*C + pos). Returns (top_w [N,k] f32,
    slot [N,k] int32 — slot id e*C + position, or the trash slot E*C for
    capacity overflow)."""
    n = xf.shape[0]
    e, k = cfg.n_experts, cfg.n_experts_used
    router_logits = q_einsum("nd,df->nf", xf, router).astype(jnp.float32)
    top_w, top_idx = jax.lax.top_k(router_logits, k)  # [N,k]
    top_w = jax.nn.softmax(top_w, axis=-1)

    # position of assignment (n, j) within its expert, in (n-major, j-minor)
    # order: running count of prior assignments to the same expert
    flat_e = top_idx.reshape(-1)  # [N*k]
    onehot = jax.nn.one_hot(flat_e, e, dtype=jnp.int32)  # [N*k, E]
    pos = (jnp.cumsum(onehot, axis=0) * onehot).sum(-1) - 1  # [N*k]
    slot = jnp.where(pos < capacity, flat_e * capacity + pos, e * capacity)
    return top_w, slot.reshape(n, k).astype(jnp.int32)


def routed_drop_fraction(
    x: jax.Array,  # [B, T, D]
    p: dict,
    cfg: ModelConfig,
    capacity_factor: float = 2.0,
    ep: int = 1,
) -> float:
    """Diagnostic: fraction of REAL (token, expert-choice) assignments that
    overflowed their expert's static capacity (landed in the trash slot)
    for THIS batch — the drop-rate observability VERDICT r4 asks for.
    Mirrors the serving path's routing exactly, INCLUDING expert
    parallelism: with ``ep`` > 1 tokens are padded/split into per-shard
    blocks routed against the per-pair capacity ``_capacity(n_pad/ep)``,
    matching ``routed_moe_ffn``'s shard_fn (a global-capacity number would
    misstate what a multi-chip mesh actually drops). Host-returning; use
    on sample batches (bench/ablation), not inside a serving step."""
    b, t, d = x.shape
    n = b * t
    k = cfg.n_experts_used
    xf = x.reshape(n, d)
    if ep <= 1:
        cap = _capacity(n, cfg, capacity_factor)
        _, slot = _route(xf, p["router"], cfg, cap)
        return float(jnp.mean((slot == cfg.n_experts * cap).astype(jnp.float32)))
    n_pad = -(-n // ep) * ep
    blk = n_pad // ep
    c_pair = _capacity(blk, cfg, capacity_factor)
    if n_pad != n:
        xf = jnp.concatenate([xf, jnp.zeros((n_pad - n, d), xf.dtype)])
    blocks = xf.reshape(ep, blk, d)
    dropped = total = 0
    for s in range(ep):
        _, slot = _route(blocks[s], p["router"], cfg, c_pair)
        real = max(0, min(n - s * blk, blk))  # pads are appended at the end
        if real == 0:
            continue
        dropped += int(jnp.sum(slot[:real] == cfg.n_experts * c_pair))
        total += real * k
    return dropped / total if total else 0.0


def _expert_swiglu(xe: jax.Array, w_gate, w_up, w_down) -> jax.Array:
    """Batched per-expert SwiGLU. xe: [E_local, C, D]."""
    gate = jax.nn.silu(q_einsum("ecd,edf->ecf", xe, w_gate))
    up = q_einsum("ecd,edf->ecf", xe, w_up)
    return q_einsum("ecf,efd->ecd", gate * up, w_down)


def routed_moe_ffn(
    x: jax.Array,  # [B, T, D]
    p: dict,  # router / w_gate_e / w_up_e / w_down_e (arrays or QTensor)
    cfg: ModelConfig,
    mesh: Mesh | None = None,
    capacity_factor: float = 2.0,
) -> jax.Array:
    """Sparse top-k MoE FFN; expert-parallel when ``mesh`` has an ep axis."""
    b, t, d = x.shape
    n = b * t
    e, k = cfg.n_experts, cfg.n_experts_used
    xf = x.reshape(n, d)

    ep = mesh.shape.get(AXIS_EP, 1) if mesh is not None else 1
    if ep <= 1:
        # single-shard: one global slot buffer (+1 trash row for drops)
        cap = _capacity(n, cfg, capacity_factor)
        top_w, slot = _route(xf, p["router"], cfg, cap)
        top_w = top_w.astype(x.dtype)
        buf = jnp.zeros((e * cap + 1, d), x.dtype)
        buf = buf.at[slot.reshape(-1)].set(
            jnp.repeat(xf, k, axis=0), mode="drop", unique_indices=True
        )
        ye = _expert_swiglu(
            buf[: e * cap].reshape(e, cap, d), p["w_gate_e"], p["w_up_e"], p["w_down_e"]
        ).reshape(e * cap, d)
        ye = jnp.concatenate([ye, jnp.zeros((1, d), ye.dtype)])  # trash row -> 0
        picked = ye[slot.reshape(-1)].reshape(n, k, d)
        out = jnp.einsum("nkd,nk->nd", picked, top_w)
        return out.reshape(b, t, d)

    e_local = e // ep
    espec = P(AXIS_EP, None, None)

    # --- all-to-all dispatch: tokens sharded on ep -------------------------
    # pad N to a multiple of ep. Pad rows DO route (uniform top-k over the
    # zero vector) and DO occupy capacity slots — correctness rests on
    # ordering: pads are appended, so within the last shard's n-major
    # cumsum every pad position comes AFTER every real token's. Pads can
    # therefore overflow to trash but never displace a real assignment,
    # and their combined outputs are discarded by out[:n]. Do not reorder
    # the padding (interleaving or per-shard padding breaks this).
    n_pad = -(-n // ep) * ep
    c_pair = _capacity(n_pad // ep, cfg, capacity_factor)
    if n_pad != n:
        xf = jnp.concatenate([xf, jnp.zeros((n_pad - n, d), xf.dtype)])

    trash = ep * e_local * c_pair
    nspec = P(AXIS_EP, None)

    def shard_fn(xf, router, w_gate, w_up, w_down):
        # xf: this shard's N/ep token block (routing is genuinely LOCAL —
        # router/top_k/cumsum run on local tokens only); expert weights
        # sharded on ep (leading E axis), router replicated. Within a
        # shard the blocks=1 slot formula g*C_pair + pos IS the local
        # all-to-all send-buffer layout dst*(E_local*C_pair) + le*C_pair
        # + pos, with the same trash id E*C_pair.
        top_w, slot = _route(xf, router, cfg, c_pair)
        top_w = top_w.astype(xf.dtype)
        nl = xf.shape[0]
        buf = jnp.zeros((trash + 1, d), xf.dtype)
        buf = buf.at[slot.reshape(-1)].set(
            jnp.repeat(xf, k, axis=0), mode="drop", unique_indices=True
        )
        send = buf[:trash].reshape(ep, e_local * c_pair, d)
        # exchange slot payloads: recv[src] = src's tokens for MY experts
        recv = jax.lax.all_to_all(send, AXIS_EP, split_axis=0, concat_axis=0)
        xe = (
            recv.reshape(ep, e_local, c_pair, d)
            .transpose(1, 0, 2, 3)
            .reshape(e_local, ep * c_pair, d)
        )
        ye = _expert_swiglu(xe, w_gate, w_up, w_down)
        back = (
            ye.reshape(e_local, ep, c_pair, d)
            .transpose(1, 0, 2, 3)
            .reshape(ep, e_local * c_pair, d)
        )
        # return outputs to their sources; row layout matches `send`
        ret = jax.lax.all_to_all(back, AXIS_EP, split_axis=0, concat_axis=0)
        ret = jnp.concatenate([ret.reshape(trash, d), jnp.zeros((1, d), ye.dtype)])
        picked = ret[slot.reshape(-1)].reshape(nl, k, d)
        return jnp.einsum("nkd,nk->nd", picked, top_w)

    router_spec = jax.tree.map(lambda _: P(None, None), p["router"])
    out = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(nspec, router_spec, espec, espec, espec),
        out_specs=nspec,
    )(xf, p["router"], p["w_gate_e"], p["w_up_e"], p["w_down_e"])
    return out[:n].reshape(b, t, d)

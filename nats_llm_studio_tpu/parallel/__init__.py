"""Device mesh + sharding rules (TP/DP/EP over ICI, DCN-ready).

The reference's only "distributed backend" is NATS itself (SURVEY.md §5):
request-reply RPC + queue groups; tensor math lives in an external engine.
Here the tensor plane is XLA collectives over ICI — GSPMD inserts
all-gather/reduce-scatter from NamedSharding annotations (jit), no NCCL
analog to hand-write — while NATS stays the control plane unchanged.
"""

from .mesh import (
    AXIS_DP,
    AXIS_EP,
    AXIS_PP,
    AXIS_SP,
    AXIS_TP,
    build_mesh,
    dp_submeshes,
    parse_mesh_spec,
    serving_mesh,
)
from .sharding import param_sharding_rules, shard_cache, shard_params

__all__ = [
    "AXIS_DP",
    "AXIS_PP",
    "AXIS_TP",
    "AXIS_EP",
    "AXIS_SP",
    "build_mesh",
    "dp_submeshes",
    "parse_mesh_spec",
    "serving_mesh",
    "param_sharding_rules",
    "shard_params",
    "shard_cache",
]

"""Per-device HBM accounting for a sharded serving config.

Answers "does this model fit this mesh?" *before* touching a device — the
fail-fast the 70B-on-v5e-8 story needs (BASELINE.md config 3: 8 x 16 GB HBM;
140 GB of bf16 weights only fit after weight-only int8). Mirrors
``sharding.param_sharding_rules`` axis-for-axis: any change there must be
reflected here (test_wquant.py pins the 70B budget).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..models.config import ModelConfig


@dataclass
class _Leaf:
    shape: tuple[int, ...]
    shard_axes: tuple[int, ...]  # which dims divide by (tp-or-ep) factors
    itemsize: int
    quantizable: bool = False


def _leaves(cfg: ModelConfig, dtype_bytes: int) -> dict[str, _Leaf]:
    d, hq, hkv, hd, ff, L, V = (
        cfg.d_model, cfg.n_heads, cfg.n_kv_heads, cfg.head_dim,
        cfg.d_ff, cfg.n_layers, cfg.vocab_size,
    )
    out: dict[str, _Leaf] = {
        "embed": _Leaf((V, d), (), dtype_bytes),
        "out_norm": _Leaf((d,), (), dtype_bytes),
        "lm_head": _Leaf((d, V), (1,), dtype_bytes, quantizable=True),
        "blocks.attn_norm": _Leaf((L, d), (), dtype_bytes),
        "blocks.ffn_norm": _Leaf((L, d), (), dtype_bytes),
        "blocks.wq": _Leaf((L, d, hq * hd), (2,), dtype_bytes, True),
        "blocks.wk": _Leaf((L, d, hkv * hd), (2,), dtype_bytes, True),
        "blocks.wv": _Leaf((L, d, hkv * hd), (2,), dtype_bytes, True),
        "blocks.wo": _Leaf((L, hq * hd, d), (1,), dtype_bytes, True),
    }
    if cfg.attn_bias:
        out |= {
            "blocks.bq": _Leaf((L, hq * hd), (1,), dtype_bytes),
            "blocks.bk": _Leaf((L, hkv * hd), (1,), dtype_bytes),
            "blocks.bv": _Leaf((L, hkv * hd), (1,), dtype_bytes),
        }
    if cfg.is_moe:
        e = cfg.n_experts
        out |= {
            "blocks.router": _Leaf((L, d, e), (), dtype_bytes),
            # dim1 divides by ep, the tp dim by tp (handled by caller factors)
            "blocks.w_gate_e": _Leaf((L, e, d, ff), (1, 3), dtype_bytes, True),
            "blocks.w_up_e": _Leaf((L, e, d, ff), (1, 3), dtype_bytes, True),
            "blocks.w_down_e": _Leaf((L, e, ff, d), (1, 2), dtype_bytes, True),
        }
    else:
        out |= {
            "blocks.w_gate": _Leaf((L, d, ff), (2,), dtype_bytes, True),
            "blocks.w_up": _Leaf((L, d, ff), (2,), dtype_bytes, True),
            "blocks.w_down": _Leaf((L, ff, d), (1,), dtype_bytes, True),
        }
    return out


def estimate_device_bytes(
    cfg: ModelConfig,
    mesh_shape: dict[str, int],
    quant: str = "none",
    batch: int = 8,
    seq_len: int | None = None,
    cache_dtype_bytes: int | None = None,
    group: int = 32,
) -> dict[str, int]:
    """Estimated peak HBM bytes per device: params + KV cache + workspace.

    ``mesh_shape`` e.g. {"tp": 8} or {"dp": 2, "ep": 4}. Sharded axes divide
    by the product of the tensor-parallel-like factors exactly as
    ``param_sharding_rules`` assigns them (tp for dense, ep x tp for experts).
    A dp factor does NOT divide anything: dp serves as independent batcher
    replicas on disjoint device slices, so each device sees one replica's
    full weights-and-cache footprint — per-chip bytes at ``dp=2,tp=2``
    equal ``tp=2``. ``quant="int4"`` prices grouped QTensor4 storage: half
    a byte per code plus an f32 scale AND zero-point per ``group``
    contraction rows.
    """
    dtype_bytes = 2 if cfg.dtype in ("bfloat16", "float16") else 4
    tp = mesh_shape.get("tp", 1)
    ep = mesh_shape.get("ep", 1)
    seq = seq_len or cfg.max_seq_len
    # replicated-KV GQA fallback (sharding.kv_replicated): when tp cannot
    # divide the KV heads, wk/wv/bk/bv and the cache stay whole per chip
    kv_tp = tp if cfg.n_kv_heads % tp == 0 else 1
    _KV_LEAVES = ("blocks.wk", "blocks.wv", "blocks.bk", "blocks.bv")

    params = 0
    for name, leaf in _leaves(cfg, dtype_bytes).items():
        n = 1
        for dim in leaf.shape:
            n *= dim
        # divide by the mesh factor on each sharded axis. For experts the
        # first sharded axis is ep, the second tp; for dense leaves it is tp.
        t = kv_tp if name in _KV_LEAVES else tp
        factors = [ep, tp] if len(leaf.shard_axes) == 2 else [t] * len(leaf.shard_axes)
        for f in factors:
            n //= f
        if quant == "int8" and leaf.quantizable:
            w_bytes = n  # int8 codes
            # scale: one f32 per output channel (last axis), same sharding
            scale_elems = n // leaf.shape[-2] if len(leaf.shape) >= 2 else 0
            params += w_bytes + scale_elems * 4
        elif quant == "int4" and leaf.quantizable:
            # packed nibbles: half a byte per code; scale + zero-point:
            # one f32 pair per group of contraction rows (wquant degrades
            # the group to divide small contraction axes — same here)
            from ..ops.wquant import effective_group

            g = effective_group(leaf.shape[-2], group)
            meta_elems = (n // leaf.shape[-2]) * (leaf.shape[-2] // g)
            params += n // 2 + meta_elems * 2 * 4
        else:
            params += n * dtype_bytes

    cb = cache_dtype_bytes or dtype_bytes
    kv = 2 * cfg.n_layers * batch * seq * cfg.n_kv_heads * cfg.head_dim * cb
    # dp is served as independent batcher REPLICAS over disjoint device
    # slices (mesh.dp_submeshes): each replica holds its own full-``batch``
    # cache, so per-DEVICE kv bytes do not divide by dp — only the kv-head
    # tp sharding (unless replicated) shrinks them
    kv //= kv_tp

    # workspace: logits [B, V] f32 (vocab sharded on tp) + activations
    # [B, T, d]-scale temporaries + collective buffers; a conservative pad
    work = batch * cfg.vocab_size * 4 // tp + 64 * 2**20
    total = params + kv + work
    return {"params": params, "kv_cache": kv, "workspace": work, "total": total}


def kv_pool_block_bytes(cfg: ModelConfig, block_tokens: int,
                        kv_quant: str | None = None, tp: int = 1) -> int:
    """Per-device bytes of ONE paged-KV pool block: K+V for ``block_tokens``
    positions across every layer. Under int8 KVQ the codes are 1 byte/elem
    plus one f32 scale per (layer, kv-head, position). ``tp`` is the factor
    actually sharding the KV-head axis (1 under the replicated-KV GQA
    fallback) — the registry prices the whole pool as blocks x this."""
    quant = (kv_quant if kv_quant is not None else cfg.kv_quant) == "int8"
    dtype_bytes = 4 if cfg.dtype == "float32" else 2
    per_pos = (
        cfg.head_dim * (1 if quant else dtype_bytes) + (4 if quant else 0)
    )
    return 2 * cfg.n_layers * cfg.n_kv_heads * block_tokens * per_pos // max(1, tp)

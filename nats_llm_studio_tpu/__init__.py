"""nats_llm_studio_tpu — a TPU-native LLM serving framework controlled over NATS.

Re-implements the capability surface of the reference (Dsouza10082/nats-llm-studio:
NATS request-reply subjects ``lmstudio.list_models`` / ``pull_model`` /
``delete_model`` / ``chat_model``, JetStream Object Store model distribution,
queue-group scale-out — see /root/reference/nats_llm_studio.go and README.md)
with an in-process JAX/XLA inference engine instead of an external LM Studio
GPU server.

Layout:
  transport/  NATS wire-protocol client + embedded broker + worker runtime
  gguf/       GGUF v3 reader/writer, block (de)quantization, tokenizer
  models/     model architectures (Llama-3, Granite, Mixtral) in pure JAX
  ops/        numeric building blocks incl. Pallas TPU kernels
  engine/     KV cache, bucketed prefill, batched decode, sampling
  parallel/   device mesh + sharding rules (TP/EP/DP) over ICI/DCN
  serve/      NATS worker: handlers, continuous batcher, streaming
  store/      object store (model blob repository) + model registry
  utils/      small shared helpers
"""

__version__ = "0.1.0"

"""Prometheus text exposition (version 0.0.4) rendering.

One renderer per scrape: metrics registered under the same family name
share a single ``# TYPE`` line regardless of how many label sets (e.g.
per-model batcher histograms) contribute samples — duplicate TYPE lines
are invalid exposition and real scrapers reject them.
"""

from __future__ import annotations

from .histogram import HistSnapshot


def _escape(value: str) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels(labels: dict[str, str] | None) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _num(v: float) -> str:
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


class PromRenderer:
    def __init__(self, default_labels: dict[str, str] | None = None) -> None:
        # family name -> (type, help, [sample lines])
        self._families: dict[str, tuple[str, str | None, list[str]]] = {}
        # merged under every sample's labels (explicit labels win): the
        # worker stamps worker_id here so a multi-worker scrape stays
        # attributable without threading the label through every call site
        self._default_labels = dict(default_labels or {})

    def _merged(self, labels: dict | None) -> dict | None:
        if not self._default_labels:
            return labels
        return {**self._default_labels, **(labels or {})}

    def _family(self, name: str, typ: str, help_: str | None) -> list[str]:
        fam = self._families.get(name)
        if fam is None:
            fam = (typ, help_, [])
            self._families[name] = fam
        elif fam[0] != typ:
            raise ValueError(f"metric {name} registered as both {fam[0]} and {typ}")
        return fam[2]

    def counter(self, name: str, value: float, labels: dict | None = None,
                help: str | None = None) -> None:
        self._family(name, "counter", help).append(
            f"{name}{_labels(self._merged(labels))} {_num(value)}"
        )

    def gauge(self, name: str, value: float, labels: dict | None = None,
              help: str | None = None) -> None:
        self._family(name, "gauge", help).append(
            f"{name}{_labels(self._merged(labels))} {_num(value)}"
        )

    def histogram(self, name: str, snap: HistSnapshot, labels: dict | None = None,
                  help: str | None = None) -> None:
        lines = self._family(name, "histogram", help)
        base = dict(self._merged(labels) or {})
        cum = 0
        for bound, c in zip(snap.bounds, snap.counts):
            cum += c
            if c == 0:
                continue  # elide empty buckets; the cumulative counts stay exact
            lines.append(
                f'{name}_bucket{_labels({**base, "le": _num(float(bound))})} {cum}'
            )
        lines.append(f'{name}_bucket{_labels({**base, "le": "+Inf"})} {snap.count}')
        lines.append(f"{name}_sum{_labels(base)} {_num(round(snap.total, 6))}")
        lines.append(f"{name}_count{_labels(base)} {snap.count}")

    def render(self) -> str:
        out: list[str] = []
        for name, (typ, help_, lines) in self._families.items():
            if help_:
                out.append(f"# HELP {name} {help_}")
            out.append(f"# TYPE {name} {typ}")
            out.extend(lines)
        return "\n".join(out) + "\n"

"""Flight recorder: a bounded ring of periodic state frames + anomaly dumps.

Prometheus gauges answer "what is the worker doing *now*"; the event ring
answers "what notable things happened"; neither answers "what was the
queue depth / pool occupancy / brownout level over the thirty seconds
*before* the crash". The flight recorder does: the batcher owner loop
samples one compact frame per ``OBS_RECORDER_INTERVAL_MS`` into a
fixed-capacity ring, and when an anomaly fires (engine restart, KV pool
exhaustion, SHED_ONLY entry, a slow request) the recorder writes the
last ``dump_window_s`` of frames plus the event-ring tail plus the
offending request's trace to a timestamped JSON under ``OBS_DUMP_DIR``,
then emits a ``flight_dump`` event pointing at the file.

Threading: frames are appended by the batcher owner thread; dumps and
reads come from the asyncio thread (debug subjects, slow-request path)
and from the registry's supervisor task. Every operation takes the
recorder's lock; ``sample``/``due`` are O(1) so the owner loop pays
nothing measurable per tick.

Import-light like the rest of ``obs/``: stdlib + the event ring only —
the batcher and transport import *us*.
"""

from __future__ import annotations

import json
import os
import threading
import time

from .events import EVENTS, emit

# dumps triggered by the same anomaly class within this window collapse
# into one file (a crash storm must not fill the disk); force=True
# bypasses the limiter for operator-requested and restart dumps
_DEFAULT_MIN_INTERVAL_S = 1.0


def _env(name: str, default: str) -> str:
    return os.environ.get(name, default)


class FlightRecorder:
    """Bounded frame ring with rate-limited anomaly dumps.

    A disabled recorder (``enabled=False``) keeps the full API but
    ``due`` is always False and ``dump`` returns None, so call sites
    never branch on configuration.
    """

    def __init__(
        self,
        *,
        capacity: int = 600,
        interval_ms: float = 250.0,
        dump_dir: str = "",
        dump_window_s: float = 30.0,
        dump_min_interval_s: float = _DEFAULT_MIN_INTERVAL_S,
        engine: str = "",
        worker_id: str = "",
        counter_fns: dict | None = None,
        enabled: bool = True,
    ):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.enabled = bool(enabled)
        self.capacity = int(capacity)
        self.interval_s = max(float(interval_ms), 1.0) / 1e3
        self.dump_dir = dump_dir
        self.dump_window_s = float(dump_window_s)
        self.dump_min_interval_s = float(dump_min_interval_s)
        self.engine = engine
        # cluster identity: stamped on every frame and dump so artifacts
        # from N workers sharing one OBS_DUMP_DIR stay attributable
        self.worker_id = worker_id
        # name -> zero-arg callable returning a number; merged into every
        # frame so process-level counters (reconnects, engine restarts)
        # line up with batcher-level state on the same timeline
        self.counter_fns = dict(counter_fns or {})
        self._buf: list[dict | None] = [None] * self.capacity
        self._seq = 0
        self._last_sample_mono = 0.0
        self._last_dump_mono = 0.0
        self._dumps_written = 0
        self._lock = threading.Lock()

    @classmethod
    def from_env(
        cls, *, engine: str = "", worker_id: str = "",
        counter_fns: dict | None = None,
    ) -> "FlightRecorder":
        return cls(
            enabled=_env("OBS_RECORDER", "1") not in ("0", "false", "off"),
            interval_ms=float(_env("OBS_RECORDER_INTERVAL_MS", "250")),
            dump_dir=_env("OBS_DUMP_DIR", ""),
            dump_window_s=float(_env("OBS_DUMP_WINDOW_S", "30")),
            engine=engine,
            worker_id=worker_id,
            counter_fns=counter_fns,
        )

    # ------------------------------------------------------------- sampling

    def due(self, now: float | None = None) -> bool:
        """Cheap owner-loop check: is the next frame's interval up?"""
        if not self.enabled:
            return False
        if now is None:
            now = time.monotonic()
        return (now - self._last_sample_mono) >= self.interval_s

    def sample(self, frame: dict, now: float | None = None) -> None:
        """Append one frame (owner thread). Stamps wall + monotonic time
        and merges the registered process counters."""
        if not self.enabled:
            return
        if now is None:
            now = time.monotonic()
        fr = {"ts": round(time.time(), 3), "mono": round(now, 3)}
        if self.worker_id:
            fr["worker_id"] = self.worker_id
        for name, fn in self.counter_fns.items():
            try:
                fr[name] = fn()
            except Exception:
                pass
        fr.update(frame)
        with self._lock:
            self._last_sample_mono = now
            self._buf[self._seq % self.capacity] = fr
            self._seq += 1

    @property
    def frames_sampled(self) -> int:
        return self._seq

    @property
    def dumps_written(self) -> int:
        return self._dumps_written

    def frames(self, last_s: float | None = None, limit: int | None = None) -> list[dict]:
        """Frames oldest-first, optionally restricted to the trailing
        ``last_s`` seconds (by monotonic stamp) or the last ``limit``."""
        with self._lock:
            start = max(0, self._seq - self.capacity)
            out = [
                fr
                for i in range(start, self._seq)
                if (fr := self._buf[i % self.capacity]) is not None
            ]
        if last_s is not None and out:
            cutoff = out[-1]["mono"] - last_s
            out = [fr for fr in out if fr["mono"] >= cutoff]
        if limit is not None and limit >= 0:
            out = out[-limit:]
        return out

    def tail(self, limit: int = 20) -> list[dict]:
        return self.frames(limit=limit)

    # ---------------------------------------------------------------- dumps

    def dump(
        self,
        reason: str,
        trace: dict | None = None,
        extra: dict | None = None,
        force: bool = False,
    ) -> str | None:
        """Write the flight-dump JSON and return its path, or None when
        disabled, no ``dump_dir`` is configured, or rate-limited.

        The dump is the incident artifact: trailing frames, event-ring
        tail, the offending request's trace, and free-form context.
        ``force`` bypasses the rate limiter (operator-requested dumps and
        restart dumps must always land).
        """
        if not self.enabled or not self.dump_dir:
            return None
        now = time.monotonic()
        with self._lock:
            if not force and (now - self._last_dump_mono) < self.dump_min_interval_s:
                return None
            self._last_dump_mono = now
            self._dumps_written += 1
            n = self._dumps_written
        doc = {
            "reason": reason,
            "engine": self.engine,
            "worker_id": self.worker_id,
            "ts": round(time.time(), 3),
            "mono": round(now, 3),
            "interval_ms": round(self.interval_s * 1e3, 3),
            "frames": self.frames(last_s=self.dump_window_s),
            "events": EVENTS.snapshot(limit=64),
            "trace": trace,
            "extra": extra or {},
        }
        fname = "flight-{:.3f}-{}-{}.json".format(time.time(), n, reason.replace("/", "_"))
        path = os.path.join(self.dump_dir, fname)
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, default=str)
            os.replace(tmp, path)
        except OSError as e:
            emit("flight_dump_error", reason=reason, error=str(e))
            return None
        emit(
            "flight_dump",
            reason=reason,
            path=path,
            engine=self.engine,
            worker_id=self.worker_id,
            frames=len(doc["frames"]),
        )
        return path

"""Fleet-level observability plane: trace assembly, metric merging, SLO burn.

One collector per cluster (or a queue group of them) does three jobs:

* **Trace assembly** — every hop (gateway, router, workers, both ends of
  the kv_export two-hop) publishes compact span batches on
  ``{prefix}.obs.spans``; the collector indexes them by trace id and
  serves the assembled parent-linked tree on request via
  ``{prefix}.debug.trace.<trace_id>``.
* **Metric aggregation** — it ingests ``{prefix}.cluster.adverts`` for
  membership, scrapes each live worker's directed ``metrics.prom``
  subject on an interval, and serves one cluster-level Prometheus
  exposition on ``{prefix}.cluster.metrics.prom``: counters/gauges sum
  across workers, histograms merge delta-first through
  :func:`obs.histogram.merge` (the same code path bench.py uses), and
  the ``worker_id`` label is dropped from merged families.
* **SLO burn-rate alerts** — objectives (cluster TTFT p95,
  served-or-retryable ratio, shed rate) are evaluated over a fast and a
  slow window; when BOTH windows burn, an ``slo_burn`` event with the
  per-worker breakdown goes out on ``{prefix}.events`` — the control
  signal an autoscaler needs (ROADMAP item 3).

Import-light like the rest of obs/: this module never imports jax or the
transport — an already-connected client (duck-typed ``subscribe`` /
``request`` / ``publish``) is injected, mirroring how ``ClusterRouter``
receives its connection. Replies are hand-built in the transport's
``{ok, error?, data?}`` envelope shape.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import re
import time
from collections import OrderedDict, deque

from .events import emit
from .histogram import bucket_pairs, merge
from .prom import PromRenderer
from .trace import Span

log = logging.getLogger("lmstudio.obs.aggregator")

_INF = float("inf")

# subjects under the prefix (mirrors serve/router.py's ADVERT_SUBJECT style)
SPANS_SUBJECT = "obs.spans"
CLUSTER_METRICS_SUBJECT = "cluster.metrics.prom"
TRACE_QUERY_PREFIX = "debug.trace"
OBS_QUEUE_GROUP = "lmstudio-obs"


# --- Prometheus exposition parsing -----------------------------------------

_TYPE_RE = re.compile(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) ([a-z]+)\s*$")
_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    return v.replace('\\"', '"').replace("\\n", "\n").replace("\\\\", "\\")


def parse_exposition(text: str) -> tuple[dict[str, str], list[tuple[str, dict, float]]]:
    """Parse exposition text into ``(types, samples)`` where ``types`` maps
    family name -> declared type and ``samples`` is a list of
    ``(sample_name, labels, value)``. Unparseable lines are skipped — the
    merger must survive a garbled worker, not die on it."""
    types: dict[str, str] = {}
    samples: list[tuple[str, dict, float]] = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            m = _TYPE_RE.match(line)
            if m:
                types.setdefault(m.group(1), m.group(2))
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name, raw_labels, raw_value = m.group(1), m.group(2), m.group(3)
        try:
            value = float(raw_value)
        except ValueError:
            continue
        labels = {k: _unescape(v) for k, v in _LABEL_RE.findall(raw_labels or "")}
        samples.append((name, labels, value))
    return types, samples


def _resolve_family(name: str, types: dict[str, str]) -> tuple[str, str, str] | None:
    """Map a sample name to ``(family, type, suffix)``; None when untyped."""
    typ = types.get(name)
    if typ is not None:
        return name, typ, ""
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            fam = name[: -len(suffix)]
            if types.get(fam) == "histogram":
                return fam, "histogram", suffix
    return None


# Ratio/rate gauges where summing across workers is meaningless (a 2-worker
# fleet at 40% MFU each is NOT at 80%): these average over the contributing
# samples instead. Totals-style families (device_ms, flops, bytes) still sum.
MEAN_GAUGE_FAMILIES = frozenset({
    "lmstudio_mfu",
    "lmstudio_mbu",
    "lmstudio_goodput_tokens_per_device_s",
})

_TENANT_LABEL = "tenant"


def _tenant_topk_env() -> int:
    try:
        return int(os.environ.get("QOS_TENANT_TOPK", "8") or 0)
    except ValueError:
        return 8


def _cap_tenant_series(
    series: dict[tuple, float], top_k: int
) -> dict[tuple, float]:
    """Cardinality cap for a merged scalar family carrying a ``tenant``
    label: keep the top-K tenants by summed value, fold the rest into
    ``tenant="other"`` — the cluster-view counterpart of
    ``serve.qos.cap_tenant_rows`` (N workers' disjoint per-worker top-Ks
    can union far past K, so the cap must be re-applied after the merge).
    Series missing the label anywhere pass through untouched."""
    totals: dict[str, float] = {}
    for k, v in series.items():
        t = dict(k).get(_TENANT_LABEL)
        if t is None:
            return series
        totals[t] = totals.get(t, 0.0) + v
    if top_k <= 0 or len(totals) <= top_k:
        return series
    keep = {
        t for t, _ in sorted(
            totals.items(), key=lambda kv: (-kv[1], kv[0])
        )[:top_k] if t != "other"
    }
    out: dict[tuple, float] = {}
    for k, v in series.items():
        lbl = dict(k)
        if lbl[_TENANT_LABEL] not in keep:
            lbl[_TENANT_LABEL] = "other"
        nk = tuple(sorted(lbl.items()))
        out[nk] = out.get(nk, 0.0) + v
    return out


def merge_into(renderer: PromRenderer, texts: list[str],
               drop_labels: tuple[str, ...] = ("worker_id",),
               tenant_topk: int | None = None) -> None:
    """Merge N workers' expositions into ``renderer`` as one cluster view.

    Counters and gauges sum across workers by their remaining label sets
    once ``drop_labels`` are removed (except :data:`MEAN_GAUGE_FAMILIES`,
    which average); histogram families merge delta-first per label group
    (each worker's cumulative buckets convert to deltas before edges
    combine — see :class:`obs.histogram.MergedHist`) and are re-rendered
    spec-clean: one TYPE line per family, cumulative monotone buckets,
    ``+Inf`` == ``_count``.
    """
    types: dict[str, str] = {}
    parsed: list[list[tuple[str, dict, float]]] = []
    for text in texts:
        t, samples = parse_exposition(text)
        for k, v in t.items():
            types.setdefault(k, v)
        parsed.append(samples)

    order: list[tuple[str, str]] = []  # (family, type) in first-seen order
    scalars: dict[str, dict[tuple, float]] = {}
    scalar_n: dict[str, dict[tuple, int]] = {}  # sample counts for means
    hist_series: dict[str, dict[tuple, dict[tuple, list]]] = {}
    hist_sums: dict[str, dict[tuple, float]] = {}

    def _key(labels: dict) -> tuple:
        return tuple(sorted(
            (k, v) for k, v in labels.items() if k not in drop_labels
        ))

    for text_idx, samples in enumerate(parsed):
        for name, labels, value in samples:
            resolved = _resolve_family(name, types)
            if resolved is None:
                continue
            family, typ, suffix = resolved
            if typ in ("counter", "gauge"):
                if (family, typ) not in order:
                    order.append((family, typ))
                scalars.setdefault(family, {})
                k = _key(labels)
                scalars[family][k] = scalars[family].get(k, 0.0) + value
                n = scalar_n.setdefault(family, {})
                n[k] = n.get(k, 0) + 1
            elif typ == "histogram":
                if (family, typ) not in order:
                    order.append((family, typ))
                groups = hist_series.setdefault(family, {})
                sums = hist_sums.setdefault(family, {})
                if suffix == "_bucket":
                    le = labels.pop("le", None)
                    if le is None:
                        continue
                    edge = _INF if le in ("+Inf", "inf") else float(le)
                    gk = _key(labels)
                    # series identity keeps worker_id (and the source text,
                    # in case two texts share one id) so cumulative counts
                    # never mix across processes before the delta conversion
                    sk = (text_idx,) + tuple(sorted(labels.items()))
                    groups.setdefault(gk, {}).setdefault(sk, []).append((edge, value))
                elif suffix == "_sum":
                    gk = _key(labels)
                    groups.setdefault(gk, {})
                    sums[gk] = sums.get(gk, 0.0) + value
                # _count is re-derived from the merged deltas: using the
                # advertised one would let a non-monotonic source break the
                # (+Inf == _count) exposition invariant

    for family, typ in order:
        if typ == "histogram":
            sums = hist_sums.get(family, {})
            for gk in sorted(hist_series.get(family, {})):
                m = merge(hist_series[family][gk].values())
                renderer.histogram(family, m.snapshot(total=sums.get(gk, 0.0)),
                                   labels=dict(gk))
        else:
            add = renderer.counter if typ == "counter" else renderer.gauge
            mean = typ == "gauge" and family in MEAN_GAUGE_FAMILIES
            series = scalars.get(family, {})
            if not mean and series and any(
                _TENANT_LABEL in dict(k) for k in series
            ):
                series = _cap_tenant_series(
                    series,
                    tenant_topk if tenant_topk is not None
                    else _tenant_topk_env(),
                )
            for k in sorted(series):
                v = series[k]
                if mean:
                    v /= max(scalar_n.get(family, {}).get(k, 1), 1)
                add(family, v, labels=dict(k))


def merge_expositions(texts: list[str],
                      drop_labels: tuple[str, ...] = ("worker_id",)) -> str:
    renderer = PromRenderer()
    merge_into(renderer, texts, drop_labels)
    return renderer.render()


# --- span assembly ----------------------------------------------------------


class SpanStore:
    """Bounded trace_id -> spans index. Oldest-touched traces evict first;
    per-trace span counts are capped so one runaway trace cannot evict the
    rest of the fleet's history."""

    def __init__(self, max_traces: int = 512, max_spans_per_trace: int = 256):
        self.max_traces = max_traces
        self.max_spans_per_trace = max_spans_per_trace
        self._traces: OrderedDict[str, dict[str, dict]] = OrderedDict()
        self.spans_total = 0
        self.dropped_total = 0

    def add(self, d: dict) -> bool:
        span = Span.from_dict(d)
        if span is None:
            self.dropped_total += 1
            return False
        spans = self._traces.get(span.trace_id)
        if spans is None:
            spans = self._traces[span.trace_id] = {}
            while len(self._traces) > self.max_traces:
                self._traces.popitem(last=False)
        else:
            self._traces.move_to_end(span.trace_id)
        if span.span_id not in spans and len(spans) >= self.max_spans_per_trace:
            self.dropped_total += 1
            return False
        spans[span.span_id] = span.to_dict()  # re-send of a span id updates it
        self.spans_total += 1
        return True

    def get(self, trace_id: str) -> list[dict]:
        return list(self._traces.get(trace_id, {}).values())

    def __len__(self) -> int:
        return len(self._traces)


def assemble_trace(trace_id: str, spans: list[dict]) -> dict:
    """Build the parent-linked tree for one trace. Spans whose parent never
    arrived (lost batch, OBS_SPANS off at one hop) surface as extra roots
    rather than disappearing; children order by wall t0 (clock skew can
    reorder siblings, never reparent them — causality lives in the links)."""
    nodes = {s["span_id"]: {**s, "children": []} for s in spans}
    roots = []
    for sid, node in nodes.items():
        parent = node.get("parent_span_id") or ""
        if parent and parent != sid and parent in nodes:
            nodes[parent]["children"].append(node)
        else:
            roots.append(node)

    def _sort(children: list[dict]) -> None:
        children.sort(key=lambda n: (n.get("t0", 0.0), n["span_id"]))
        for c in children:
            _sort(c["children"])

    _sort(roots)
    return {"trace_id": trace_id, "span_count": len(nodes), "roots": roots}


# --- SLO burn-rate evaluation ----------------------------------------------


class SloEvaluator:
    """Multi-window burn-rate evaluation over scraped worker snapshots.

    ``observe()`` is fed one ``{worker_id: sample}`` dict per scrape tick
    (see :meth:`sample_from_exposition`); windowed deltas subtract the
    cumulative counters/buckets at the window start from the newest ones,
    per worker, so restarts (counter resets) clamp to zero instead of
    going negative. An alert fires only when BOTH the fast and the slow
    window burn at >= 1.0 — the classic guard against paging on a blip
    (fast-only) or on long-stale history (slow-only).
    """

    OBJECTIVES = ("ttft_p95", "served_ratio", "shed_rate")

    def __init__(self, *, ttft_p95_ms: float = 2000.0, window_s: float = 60.0,
                 served_ratio: float = 0.99, shed_ratio: float = 0.05,
                 fast_window_s: float | None = None,
                 min_alert_gap_s: float | None = None):
        self.ttft_p95_ms = ttft_p95_ms
        self.window_s = window_s
        self.served_ratio = served_ratio
        self.shed_ratio = shed_ratio
        self.fast_window_s = min(
            window_s, fast_window_s if fast_window_s is not None
            else max(1.0, window_s / 12.0)
        )
        self.min_alert_gap_s = (min_alert_gap_s if min_alert_gap_s is not None
                                else self.fast_window_s)
        self._snaps: deque[tuple[float, dict[str, dict]]] = deque()
        self._last_alert: dict[str, float] = {}
        # latest burn per objective, for the cluster exposition gauges
        self.last_burns: dict[str, dict[str, float]] = {}

    @staticmethod
    def sample_from_exposition(text: str) -> dict:
        """Extract the cumulative signals one worker contributes to the
        objectives: TTFT buckets, admitted requests, sheds, retryable
        in-flight failures."""
        def family_sum(family: str) -> float:
            return sum(
                float(line.rsplit(None, 1)[1])
                for line in text.splitlines()
                if line.startswith(family + "{") or line.startswith(family + " ")
            )

        return {
            "ttft": bucket_pairs(text, "lmstudio_ttft_ms"),
            "requests": family_sum("lmstudio_batcher_requests_total"),
            "sheds": family_sum("lmstudio_batcher_shed_by_cause_total"),
            "failed": family_sum("lmstudio_inflight_failed_retryable_total"),
        }

    @staticmethod
    def _cum_at(pairs: list[tuple[float, float]], edge: float) -> float:
        """Cumulative count at ``edge`` for a sorted elided bucket list:
        the renderer only prints edges whose delta is non-zero, so the
        cumulative function is exactly the value at the largest printed
        edge <= ``edge`` (0 before the first)."""
        cum = 0.0
        for e, c in pairs:
            if e > edge:
                break
            cum = c
        return cum

    def observe(self, now: float,
                per_worker: dict[str, dict]) -> list[dict]:
        """Record one scrape tick and return any alerts to publish."""
        self._snaps.append((now, per_worker))
        # keep exactly one snapshot at/older than the slow window start so
        # the baseline lookup always has an anchor
        while len(self._snaps) >= 2 and self._snaps[1][0] <= now - self.window_s:
            self._snaps.popleft()

        slow = self._window_deltas(now, self.window_s)
        fast = self._window_deltas(now, self.fast_window_s)
        alerts: list[dict] = []
        for objective in self.OBJECTIVES:
            burn_fast, observed_fast = self._burn(objective, fast)
            burn_slow, observed_slow = self._burn(objective, slow)
            self.last_burns[objective] = {
                "fast": round(burn_fast, 4), "slow": round(burn_slow, 4),
            }
            if burn_fast < 1.0 or burn_slow < 1.0:
                continue
            if now - self._last_alert.get(objective, -_INF) < self.min_alert_gap_s:
                continue
            self._last_alert[objective] = now
            alerts.append({
                "objective": objective,
                "target": self._target(objective),
                "burn_fast": round(burn_fast, 4),
                "burn_slow": round(burn_slow, 4),
                "observed_fast": round(observed_fast, 4),
                "observed_slow": round(observed_slow, 4),
                "window_s": self.window_s,
                "fast_window_s": self.fast_window_s,
                "per_worker": {
                    wid: self._worker_breakdown(d) for wid, d in slow.items()
                },
            })
        return alerts

    def _target(self, objective: str) -> float:
        return {"ttft_p95": self.ttft_p95_ms, "served_ratio": self.served_ratio,
                "shed_rate": self.shed_ratio}[objective]

    def _window_deltas(self, now: float, win_s: float) -> dict[str, dict]:
        if not self._snaps:
            return {}
        base = self._snaps[0][1]
        for t, snap in self._snaps:
            if t <= now - win_s:
                base = snap
            else:
                break
        cur = self._snaps[-1][1]
        out: dict[str, dict] = {}
        for wid, s in cur.items():
            b = base.get(wid) or {"ttft": [], "requests": 0.0, "sheds": 0.0,
                                  "failed": 0.0}
            base_pairs = sorted(b["ttft"])
            ttft = [
                (edge, max(0.0, cum - self._cum_at(base_pairs, edge)))
                for edge, cum in sorted(s["ttft"])
            ]
            out[wid] = {
                "ttft": ttft,
                "requests": max(0.0, s["requests"] - b["requests"]),
                "sheds": max(0.0, s["sheds"] - b["sheds"]),
                "failed": max(0.0, s["failed"] - b["failed"]),
            }
        return out

    @staticmethod
    def _worker_breakdown(d: dict) -> dict:
        m = merge([d["ttft"]])
        return {
            "ttft_p95_ms": round(m.quantile(0.95), 3),
            "ttft_count": int(m.count),
            "requests": d["requests"],
            "sheds": d["sheds"],
            "failed": d["failed"],
        }

    def _burn(self, objective: str, deltas: dict[str, dict]) -> tuple[float, float]:
        """``(burn_rate, observed_value)`` for one objective over one
        window's per-worker deltas. An idle window burns 0.0 — no traffic
        is not an SLO violation."""
        requests = sum(d["requests"] for d in deltas.values())
        if objective == "ttft_p95":
            m = merge(d["ttft"] for d in deltas.values())
            if m.count <= 0:
                return 0.0, 0.0
            p95 = m.quantile(0.95)
            return p95 / max(1e-9, self.ttft_p95_ms), p95
        if requests <= 0:
            return 0.0, 0.0 if objective == "shed_rate" else 1.0
        sheds = sum(d["sheds"] for d in deltas.values())
        failed = sum(d["failed"] for d in deltas.values())
        if objective == "served_ratio":
            bad_frac = min(1.0, (sheds + failed) / requests)
            budget = max(1e-9, 1.0 - self.served_ratio)
            return bad_frac / budget, 1.0 - bad_frac
        shed_frac = min(1.0, sheds / requests)
        return shed_frac / max(1e-9, self.shed_ratio), shed_frac


# --- the collector ----------------------------------------------------------


class Aggregator:
    """The cluster collector; see the module docstring for the three jobs.

    ``nc`` is an already-connected client owned by the caller (main.py's
    ``obs`` subcommand, an embedding router process, or a test harness);
    ``start()``/``stop()`` manage only subscriptions and the scrape loop.
    """

    def __init__(self, nc, *, prefix: str = "lmstudio",
                 scrape_interval_s: float = 2.0, stale_after_s: float = 5.0,
                 scrape_timeout_s: float | None = None,
                 slo: SloEvaluator | None = None,
                 slo_ttft_p95_ms: float = 2000.0, slo_window_s: float = 60.0,
                 slo_served_ratio: float = 0.99, slo_shed_ratio: float = 0.05,
                 extra_expositions: list | None = None):
        self.nc = nc
        self.prefix = prefix
        # zero-arg callables, each returning exposition text merged into
        # render_cluster() — how an embedded autoscaler's families ride the
        # cluster scrape without a second process (ISSUE 15)
        self.extra_expositions = list(extra_expositions or [])
        self.scrape_interval_s = scrape_interval_s
        self.stale_after_s = stale_after_s
        self.scrape_timeout_s = (scrape_timeout_s if scrape_timeout_s is not None
                                 else max(1.0, scrape_interval_s))
        self.slo = slo or SloEvaluator(
            ttft_p95_ms=slo_ttft_p95_ms, window_s=slo_window_s,
            served_ratio=slo_served_ratio, shed_ratio=slo_shed_ratio,
            # the fast window cannot resolve faster than the scrape cadence
            fast_window_s=max(2 * scrape_interval_s, slo_window_s / 12.0),
        )
        self.spans = SpanStore()
        self._members: dict[str, dict] = {}  # wid -> {"mono": t, "advert": {}}
        self._last_texts: dict[str, str] = {}
        self._cluster_ttft_p95 = 0.0
        self.scrapes_total = 0
        self.scrape_errors_total = 0
        self.span_batches_total = 0
        self.alerts_total = 0
        self._subs: list = []
        self._task: asyncio.Task | None = None

    # -- lifecycle -----------------------------------------------------------

    async def start(self, *, scrape_loop: bool = True) -> None:
        sub = await self.nc.subscribe(f"{self.prefix}.cluster.adverts",
                                      cb=self._on_advert)
        self._subs.append(sub)
        sub = await self.nc.subscribe(f"{self.prefix}.{SPANS_SUBJECT}",
                                      cb=self._on_spans)
        self._subs.append(sub)
        # request/reply surfaces share a queue group: replicas all hold the
        # full span/metric state (spans and adverts are broadcast), so any
        # one member can answer
        sub = await self.nc.subscribe(f"{self.prefix}.{CLUSTER_METRICS_SUBJECT}",
                                      queue=OBS_QUEUE_GROUP,
                                      cb=self._on_cluster_metrics)
        self._subs.append(sub)
        sub = await self.nc.subscribe(f"{self.prefix}.{TRACE_QUERY_PREFIX}.>",
                                      queue=OBS_QUEUE_GROUP,
                                      cb=self._on_trace_query)
        self._subs.append(sub)
        if scrape_loop:
            self._task = asyncio.ensure_future(self._scrape_loop())
        log.info("aggregator up: prefix=%s scrape=%.1fs", self.prefix,
                 self.scrape_interval_s)

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        for sub in self._subs:
            try:
                await sub.unsubscribe()
            except (ConnectionError, ValueError):
                pass
        self._subs.clear()

    # -- membership ----------------------------------------------------------

    async def _on_advert(self, msg) -> None:
        try:
            d = json.loads(msg.payload or b"{}")
        except ValueError:
            return
        wid = d.get("worker_id") if isinstance(d, dict) else None
        if not wid:
            return
        self._members[wid] = {"mono": time.monotonic(), "advert": d}

    def live_workers(self) -> list[str]:
        """Workers advertising within the staleness window. Draining workers
        stay scrapable — their final counters are exactly what a drain
        post-mortem needs. Gateway adverts (role "gateway") are scraped
        (see :meth:`_scrape_targets`) but are not workers: they must not
        count toward ``lmstudio_cluster_workers`` or scaling signals."""
        now = time.monotonic()
        return sorted(
            wid for wid, m in self._members.items()
            if now - m["mono"] <= self.stale_after_s
            and m["advert"].get("role") != "gateway"
        )

    def _scrape_targets(self) -> list[str]:
        """Everything advertising a directed ``metrics.prom`` subject —
        live workers plus gateway-role members, whose lmstudio_gateway_*
        families fold into the cluster exposition."""
        now = time.monotonic()
        return sorted(
            wid for wid, m in self._members.items()
            if now - m["mono"] <= self.stale_after_s
        )

    # -- scraping + merging --------------------------------------------------

    async def _scrape_loop(self) -> None:
        try:
            while True:
                await asyncio.sleep(self.scrape_interval_s)
                try:
                    await self.scrape_once()
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 — the loop must survive a bad tick
                    log.exception("scrape tick failed")
        except asyncio.CancelledError:
            return

    async def scrape_once(self) -> dict[str, str]:
        """One scrape tick: request every advert member's directed exposition
        (workers AND gateway-role members), refresh the merged view, advance
        the SLO windows, publish alerts. Returns the WORKER texts only —
        gateway expositions fold into :meth:`render_cluster` but carry no
        serving signals, so they stay out of the SLO windows and out of the
        callers' per-worker view."""
        # prune long-dead members so the map cannot grow without bound
        now_mono = time.monotonic()
        for wid in [w for w, m in self._members.items()
                    if now_mono - m["mono"] > 10 * self.stale_after_s]:
            del self._members[wid]
        members = self._scrape_targets()
        results = await asyncio.gather(
            *(self.nc.request(f"{self.prefix}.worker.{wid}.metrics.prom", b"",
                              timeout=self.scrape_timeout_s)
              for wid in members),
            return_exceptions=True,
        )
        texts: dict[str, str] = {}
        for wid, res in zip(members, results):
            if isinstance(res, BaseException):
                self.scrape_errors_total += 1
            else:
                texts[wid] = res.payload.decode("utf-8", errors="replace")
        self.scrapes_total += 1
        self._last_texts = texts
        workers = set(self.live_workers())
        texts = {wid: t for wid, t in texts.items() if wid in workers}

        per_worker = {
            wid: SloEvaluator.sample_from_exposition(t) for wid, t in texts.items()
        }
        self._cluster_ttft_p95 = merge(
            s["ttft"] for s in per_worker.values()
        ).quantile(0.95)
        for alert in self.slo.observe(time.monotonic(), per_worker):
            await self._publish_alert(alert)
        return texts

    def render_cluster(self) -> str:
        """The merged cluster exposition: every worker family (minus the
        worker_id label) plus the aggregator's own lmstudio_cluster_*
        families."""
        r = PromRenderer()
        texts = [self._last_texts[w] for w in sorted(self._last_texts)]
        for fn in self.extra_expositions:
            try:
                texts.append(fn())
            except Exception:  # noqa: BLE001 — a co-tenant must not break the scrape
                log.exception("extra exposition source failed")
        merge_into(r, texts)
        r.gauge("lmstudio_cluster_workers", len(self.live_workers()),
                help="workers advertising within the staleness window")
        r.counter("lmstudio_cluster_scrapes_total", self.scrapes_total,
                  help="aggregator scrape ticks")
        r.counter("lmstudio_cluster_scrape_errors_total",
                  self.scrape_errors_total,
                  help="per-worker scrape requests that timed out or failed")
        r.counter("lmstudio_cluster_span_batches_total", self.span_batches_total,
                  help="span batches ingested from {prefix}.obs.spans")
        r.counter("lmstudio_cluster_spans_total", self.spans.spans_total,
                  help="individual spans ingested")
        r.gauge("lmstudio_cluster_traces", len(self.spans),
                help="distinct trace ids currently held for assembly")
        r.gauge("lmstudio_cluster_ttft_p95_ms",
                round(self._cluster_ttft_p95, 3),
                help="cluster TTFT p95 merged delta-first across the last "
                     "scrape (upper bucket edge, same code path as bench.py)")
        r.counter("lmstudio_cluster_slo_alerts_total", self.alerts_total,
                  help="slo_burn events published")
        for objective, burns in sorted(self.slo.last_burns.items()):
            for window in ("fast", "slow"):
                r.gauge("lmstudio_cluster_slo_burn", burns[window],
                        labels={"objective": objective, "window": window},
                        help="latest burn rate per objective and window "
                             "(>= 1.0 in BOTH windows fires slo_burn)")
        return r.render()

    # -- alerts --------------------------------------------------------------

    async def _publish_alert(self, alert: dict) -> None:
        self.alerts_total += 1
        emit("slo_burn", **alert)
        log.warning("slo_burn: %s burn_fast=%.2f burn_slow=%.2f",
                    alert["objective"], alert["burn_fast"], alert["burn_slow"])
        try:
            await self.nc.publish(
                f"{self.prefix}.events",
                json.dumps({"kind": "slo_burn", **alert},
                           separators=(",", ":")).encode(),
            )
        except (ConnectionError, ValueError):
            pass  # reconnect in flight; the alert still sits in the ring

    # -- request/reply surfaces ----------------------------------------------

    async def _on_spans(self, msg) -> None:
        try:
            d = json.loads(msg.payload or b"{}")
        except ValueError:
            return
        spans = d.get("spans") if isinstance(d, dict) else None
        if not isinstance(spans, list):
            return
        self.span_batches_total += 1
        for s in spans:
            self.spans.add(s)

    async def _on_cluster_metrics(self, msg) -> None:
        if not msg.reply:
            return
        try:
            await msg.respond(self.render_cluster().encode())
        except (ConnectionError, ValueError):
            pass

    async def _on_trace_query(self, msg) -> None:
        if not msg.reply:
            return
        want = f"{self.prefix}.{TRACE_QUERY_PREFIX}."
        trace_id = (msg.subject[len(want):]
                    if msg.subject.startswith(want) else "")
        spans = self.spans.get(trace_id)
        if spans:
            env: dict = {"ok": True, "data": assemble_trace(trace_id, spans)}
        else:
            env = {"ok": False,
                   "error": f"no spans recorded for trace {trace_id!r}"}
        try:
            await msg.respond(json.dumps(env, separators=(",", ":")).encode())
        except (ConnectionError, ValueError):
            pass

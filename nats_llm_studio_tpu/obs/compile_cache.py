"""Process-wide XLA compile-cache hit/miss counters.

JAX's persistent compilation cache (config.configure_jax wires
``JAX_COMPILE_CACHE_DIR``) reports hits and misses only through
``jax.monitoring`` events — invisible to operators unless something
listens. This module turns them into two monotonic counters the worker
exposes as ``lmstudio_compile_cache_{hits,misses}_total``, which is how
you tell "the restart re-jitted the whole grid from the cache in
seconds" apart from "the cache was cold/evicted and every program paid a
full XLA compile".

Import-light like the rest of obs/: jax is imported inside the installer
only, and installation is idempotent (the worker calls it at startup;
tests may call it again freely).
"""

from __future__ import annotations

import threading

_lock = threading.Lock()
_counts = {"hits": 0, "misses": 0}
_installed = False

# jax.monitoring event suffixes → counter keys (jax 0.4.x names the
# events /jax/compilation_cache/cache_{hits,misses})
_EVENT_KEYS = {"cache_hits": "hits", "cache_misses": "misses"}


def _on_event(event: str, **kwargs) -> None:
    key = _EVENT_KEYS.get(event.rsplit("/", 1)[-1])
    if key is not None:
        with _lock:
            _counts[key] += 1


def install_compile_cache_listener() -> bool:
    """Register the jax.monitoring listener once per process. Returns True
    when the listener is (now) installed, False when jax.monitoring is
    unavailable. Safe to call repeatedly."""
    global _installed
    with _lock:
        if _installed:
            return True
    try:
        from jax import monitoring
    except Exception:  # noqa: BLE001 — counters just stay at zero
        return False
    with _lock:
        if _installed:  # lost a race to another caller
            return True
        monitoring.register_event_listener(_on_event)
        _installed = True
    return True


def compile_cache_counts() -> dict[str, int]:
    """Snapshot of {hits, misses} since install (zeros before install)."""
    with _lock:
        return dict(_counts)

"""Compute-efficiency plane: roofline accounting and HBM ledger reconciliation.

Three small, dependency-light pieces that the batcher / registry / worker wire
together into MFU / MBU / goodput metrics:

* **Program cost extraction** — ``extract_dispatch_cost`` pulls flops and
  bytes-accessed out of XLA's cost analysis for a jitted program *before* it is
  dispatched (programs use ``donate_argnums``, so inputs are invalid after the
  call).  Results are cached per (program, shape-bucket) by the batcher's timer
  wrapper; any failure caches ``None`` forever so serving never pays twice.
* **Chip peak table** — ``chip_peaks`` resolves peak bf16 FLOP/s and HBM
  bytes/s for the local accelerator (v4 / v5e / v5p / v6e), overridable with
  ``TPU_PEAK_FLOPS`` / ``TPU_HBM_GBPS``, with a deliberately modest CPU
  fallback so smoke runs still report nonzero MFU / MBU.
* **HBM ledger** — ``HbmLedger`` reconciles the sum of priced memory
  components (weights, block pool, prefix cache, workspace slack) against the
  device allocator's ``bytes_in_use`` on every flight-recorder tick and fires
  an ``hbm_drift`` event when unexplained bytes grow monotonically past a
  threshold: a leak detector for the pool / CoW / handoff paths.

Everything here is host-side accounting: no jax import at module load, no
device work beyond ``memory_stats()`` / one-time ``lower()`` calls.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Callable

__all__ = [
    "PREFILL_PROGRAMS",
    "DECODE_PROGRAMS",
    "SPEC_PROGRAMS",
    "WASTE_CATEGORIES",
    "classify_program",
    "program_base",
    "program_family",
    "efficiency_enabled",
    "chip_peaks",
    "resolve_chip_peaks",
    "extract_dispatch_cost",
    "dispatch_shape_key",
    "RollingUtilization",
    "HbmLedger",
]

# -- program classification ----------------------------------------------------
#
# Names must match the keys the batcher passes to ``BatcherStats.record_program``
# (the ``_timed`` wrapper names in serve/batcher.py).  Prefill programs are
# compute-bound (MFU is the headline); decode programs are bandwidth-bound
# (MBU is the headline).  Anything else (ring compaction, CoW block copies,
# warmup) lands in "other" and is reported as waste unless request-attributed.

PREFILL_PROGRAMS = frozenset(
    {
        "prefill1",
        "prefill_full",
        "admit_fused",
        "admit_many_fused",
        "finish_admit",
        "prefill_chunk_group",
        "select_end",
        "finish_admit_group",
        "write_prefix_block",
        "sample_first",
        "admit_fused_paged",
        "admit_many_fused_paged",
        "finish_admit_paged",
        "finish_admit_group_paged",
        "fill_row_chunk",
    }
)

DECODE_PROGRAMS = frozenset(
    {
        "decode",
        "decode_pos",
        "decode_pos_ext",
        "decode_pos_paged",
        "decode_pos_paged_ext",
        # Pallas paged-decode kernel dispatches (serve/batcher.py under
        # DECODE_KERNEL=pallas) — ledgered apart from the decode_pos_paged
        # gather-view path so the roofline can attribute the kernel swap
        "decode_pallas",
        "decode_pallas_ext",
        "spec_verify",
        "spec_verify_paged",
        "spec_verify_pallas",
    }
)

SPEC_PROGRAMS = frozenset(
    {"spec_verify", "spec_verify_paged", "spec_verify_pallas"}
)

# Outcome categories for the device-time ledger.  "other" absorbs dispatches
# with no request context (warmup, compaction, CoW copies).
WASTE_CATEGORIES = (
    "served",
    "shed_after_prefill",
    "cancelled",
    "deadline_abort",
    "spec_rejected",
    "disagg_fallback_reprefill",
    "other",
)


# Program-family suffixes the batcher appends to the base dispatch names:
# ``_moe`` when the forward runs capacity-factor routed MoE (appended at
# wrap time — a property of the model), ``_ring`` when a prefill_full
# dispatch takes the sp ring-attention path (appended per dispatch — a
# property of that prompt's length bucket).  Classification strips them so
# the roofline ledger keeps one prefill/decode split while metrics retain
# the tagged names.
_FAMILY_SUFFIXES = ("_ring", "_moe")


def program_base(name: str) -> str:
    """Strip family suffixes: ``prefill_full_moe_ring`` -> ``prefill_full``."""
    changed = True
    while changed:
        changed = False
        for sfx in _FAMILY_SUFFIXES:
            if name.endswith(sfx) and name[: -len(sfx)]:
                name = name[: -len(sfx)]
                changed = True
    return name


def program_family(name: str) -> str:
    """Coarse family tag for a recorded program name: ``ring_prefill`` when
    the dispatch ran the sequence-parallel ring, ``moe_routed`` when the
    forward used routed experts, ``dense`` otherwise."""
    if name.endswith("_ring") or "_ring_" in name:
        return "ring_prefill"
    if name.endswith("_moe") or "_moe_" in name:
        return "moe_routed"
    return "dense"


def classify_program(name: str) -> str:
    name = program_base(name)
    if name in PREFILL_PROGRAMS:
        return "prefill"
    if name in DECODE_PROGRAMS:
        return "decode"
    return "other"


def efficiency_enabled() -> bool:
    """EFFICIENCY=0|false|off kills the whole plane (cost extraction + ledger)."""
    return os.environ.get("EFFICIENCY", "1").strip().lower() not in ("0", "false", "off", "no")


# -- chip peak table -----------------------------------------------------------
#
# (substring of jax device_kind, peak bf16 FLOP/s, peak HBM bytes/s).  Matched
# case-insensitively, first hit wins, so more specific kinds come first.

_CHIP_PEAKS: tuple[tuple[str, float, float], ...] = (
    ("v6e", 918e12, 1640e9),
    ("v5p", 459e12, 2765e9),
    ("v5e", 197e12, 819e9),
    ("v5 lite", 197e12, 819e9),
    ("v5litepod", 197e12, 819e9),
    ("v4", 275e12, 1228e9),
)

# CPU fallback so smoke/bench runs on the CPU backend still produce nonzero
# (if not meaningful) MFU/MBU.  Deliberately modest: ~0.5 TFLOP/s, 50 GB/s.
_CPU_PEAKS = (5e11, 5e10)

_peaks_lock = threading.Lock()
_peaks_cache: tuple[float, float] | None = None


def resolve_chip_peaks(device_kind: str) -> tuple[float, float]:
    """Pure lookup: (peak_flops_per_s, peak_hbm_bytes_per_s) for a device kind.

    Env overrides win over the table; unknown kinds get the CPU fallback.
    ``TPU_PEAK_FLOPS`` is raw FLOP/s; ``TPU_HBM_GBPS`` is GB/s (decimal).
    """
    flops = bw = 0.0
    kind = (device_kind or "").lower()
    for sub, f, b in _CHIP_PEAKS:
        if sub in kind:
            flops, bw = f, b
            break
    else:
        flops, bw = _CPU_PEAKS
    try:
        env_f = os.environ.get("TPU_PEAK_FLOPS")
        if env_f:
            flops = float(env_f)
    except ValueError:
        pass
    try:
        env_b = os.environ.get("TPU_HBM_GBPS")
        if env_b:
            bw = float(env_b) * 1e9
    except ValueError:
        pass
    return (max(flops, 1.0), max(bw, 1.0))


def chip_peaks() -> tuple[float, float]:
    """Resolve and cache peaks for the local jax backend (lazy; never raises)."""
    global _peaks_cache
    with _peaks_lock:
        if _peaks_cache is not None:
            return _peaks_cache
    kind = ""
    try:
        import jax

        kind = getattr(jax.devices()[0], "device_kind", "") or ""
    except Exception:
        kind = ""
    peaks = resolve_chip_peaks(kind)
    with _peaks_lock:
        _peaks_cache = peaks
    return peaks


def _reset_peaks_cache() -> None:  # test hook
    global _peaks_cache
    with _peaks_lock:
        _peaks_cache = None


# -- per-program cost extraction -----------------------------------------------


def dispatch_shape_key(args: tuple, kwargs: dict) -> tuple:
    """Cheap structural key for a dispatch: shapes/dtypes for arrays, raw values
    for static scalars.  Two dispatches with equal keys hit the same XLA
    executable, so their cost analysis is identical."""

    def sig(a: Any):
        shp = getattr(a, "shape", None)
        if shp is not None:
            return (tuple(shp), str(getattr(a, "dtype", "")))
        if a is None or isinstance(a, (int, float, bool, str)):
            return a
        return type(a).__name__

    kw = tuple(sorted((k, sig(v)) for k, v in kwargs.items())) if kwargs else ()
    return (tuple(sig(a) for a in args), kw)


def extract_dispatch_cost(fn: Any, args: tuple, kwargs: dict) -> tuple[float, float] | None:
    """(flops, bytes_accessed) for one dispatch of a jitted ``fn``, or None.

    Must run *before* the dispatch: programs donate input buffers, which are
    invalid afterwards.  Uses ``lowered.cost_analysis()`` ONLY — no backend
    compile: ``lowered.compile()`` would not populate the jit's own
    executable cache, so probing through it would pay every program's
    compile twice.  A program whose analysis reads all-zero is simply not
    costed (callers cache the None).  Never raises.
    """
    try:
        lowered = fn.lower(*args, **kwargs)
    except Exception:
        return None

    def _pick(ca: Any) -> tuple[float, float]:
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if not isinstance(ca, dict):
            return (0.0, 0.0)
        try:
            f = float(ca.get("flops", 0.0) or 0.0)
        except (TypeError, ValueError):
            f = 0.0
        try:
            b = float(ca.get("bytes accessed", 0.0) or 0.0)
        except (TypeError, ValueError):
            b = 0.0
        return (f, b)

    flops = bytes_ = 0.0
    try:
        flops, bytes_ = _pick(lowered.cost_analysis())
    except Exception:
        return None
    if flops <= 0.0 and bytes_ <= 0.0:
        return None
    return (max(flops, 0.0), max(bytes_, 0.0))


# -- rolling utilization -------------------------------------------------------


class RollingUtilization:
    """Flops/bytes over a rolling wall-clock window → achieved rates.

    ``add`` is called from the batcher owner thread per dispatch; ``rates`` /
    ``utilization`` from scrape threads, hence the lock.  The denominator is
    wall time spanned by the retained samples (standard MFU definition), not
    summed host dispatch time — with the async dispatch pipeline the latter
    wildly overstates utilization.
    """

    def __init__(self, window_s: float = 10.0, clock: Callable[[], float] = time.monotonic):
        self.window_s = float(window_s)
        self.clock = clock
        self._dq: deque[tuple[float, float, float]] = deque()
        self._lock = threading.Lock()

    def add(self, flops: float, bytes_: float) -> None:
        now = self.clock()
        with self._lock:
            self._dq.append((now, float(flops), float(bytes_)))
            cutoff = now - self.window_s
            while self._dq and self._dq[0][0] < cutoff:
                self._dq.popleft()

    def rates(self) -> tuple[float, float]:
        """(flops_per_s, bytes_per_s) over the window; zeros when idle."""
        now = self.clock()
        with self._lock:
            cutoff = now - self.window_s
            while self._dq and self._dq[0][0] < cutoff:
                self._dq.popleft()
            if not self._dq:
                return (0.0, 0.0)
            span = now - self._dq[0][0]
            if span <= 0.0:
                return (0.0, 0.0)
            fl = sum(s[1] for s in self._dq)
            by = sum(s[2] for s in self._dq)
        return (fl / span, by / span)

    def utilization(self, peaks: tuple[float, float] | None = None) -> tuple[float, float]:
        """(mfu, mbu) in [0, 1] against chip peaks (clamped at 1.0)."""
        pf, pb = peaks if peaks is not None else chip_peaks()
        rf, rb = self.rates()
        return (min(rf / max(pf, 1.0), 1.0), min(rb / max(pb, 1.0), 1.0))


# -- HBM ledger ----------------------------------------------------------------


def _default_bytes_in_use() -> int | None:
    try:
        import jax

        ms = jax.local_devices()[0].memory_stats()
        if not ms:
            return None
        v = ms.get("bytes_in_use")
        return int(v) if v is not None else None
    except Exception:
        return None


class HbmLedger:
    """Reconcile priced HBM components against the allocator's bytes_in_use.

    ``components`` maps a name to a zero-arg callable returning its current
    priced bytes.  ``tick()`` (called per flight-recorder frame) samples the
    allocator, computes ``unexplained = bytes_in_use - sum(priced)``, and fires
    one ``hbm_drift`` event when unexplained bytes grow monotonically above
    ``drift_threshold_bytes`` (vs. the running baseline) for ``sustain_ticks``
    consecutive samples — then re-baselines so a stable-but-larger footprint
    doesn't alarm forever.  On backends without ``memory_stats`` (CPU) every
    sample is zeros and no event can fire.
    """

    def __init__(
        self,
        components: dict[str, Callable[[], int]],
        *,
        bytes_in_use_fn: Callable[[], int | None] | None = None,
        drift_threshold_bytes: int | None = None,
        sustain_ticks: int = 4,
        emit_fn: Callable[..., Any] | None = None,
    ):
        self.components = dict(components)
        self.bytes_in_use_fn = bytes_in_use_fn or _default_bytes_in_use
        if drift_threshold_bytes is None:
            try:
                drift_threshold_bytes = int(
                    os.environ.get("HBM_DRIFT_THRESHOLD_BYTES", str(64 << 20))
                )
            except ValueError:
                drift_threshold_bytes = 64 << 20
        self.drift_threshold_bytes = int(drift_threshold_bytes)
        self.sustain_ticks = max(int(sustain_ticks), 1)
        self.emit_fn = emit_fn
        self.drift_events = 0
        self._baseline: int | None = None
        self._last_unexplained: int | None = None
        self._grow_ticks = 0
        self._last: dict[str, Any] = {}
        self._lock = threading.Lock()

    def last_sample(self) -> dict[str, Any]:
        with self._lock:
            return dict(self._last)

    def tick(self) -> int:
        """Sample + reconcile; returns current drift-above-baseline bytes (>= 0)."""
        priced: dict[str, int] = {}
        for name, fn in self.components.items():
            try:
                priced[name] = int(fn() or 0)
            except Exception:
                priced[name] = 0
        total = sum(priced.values())
        try:
            in_use = self.bytes_in_use_fn()
        except Exception:
            in_use = None
        if in_use is None:
            sample = {
                "bytes_in_use": 0,
                "priced_bytes": total,
                "unexplained_bytes": 0,
                "drift_bytes": 0,
                "components": priced,
            }
            with self._lock:
                self._last = sample
            return 0
        unexplained = int(in_use) - total
        fire = False
        with self._lock:
            if self._baseline is None:
                self._baseline = unexplained
            growth = unexplained - self._baseline
            monotone = self._last_unexplained is None or unexplained >= self._last_unexplained
            if growth > self.drift_threshold_bytes and monotone:
                self._grow_ticks += 1
            elif not monotone:
                self._grow_ticks = 0
            self._last_unexplained = unexplained
            if self._grow_ticks >= self.sustain_ticks:
                fire = True
                self.drift_events += 1
                self._baseline = unexplained
                self._grow_ticks = 0
            drift = max(growth, 0)
            self._last = {
                "bytes_in_use": int(in_use),
                "priced_bytes": total,
                "unexplained_bytes": unexplained,
                "drift_bytes": drift,
                "components": priced,
            }
        if fire and self.emit_fn is not None:
            try:
                self.emit_fn(
                    "hbm_drift",
                    bytes_in_use=int(in_use),
                    priced_bytes=total,
                    unexplained_bytes=unexplained,
                    growth_bytes=growth,
                )
            except Exception:
                pass
        return drift

"""Observability: trace context, bounded histograms, events, exposition.

The subsystem PR 1 threads through every layer — see histogram.py,
trace.py, events.py, prom.py, aggregator.py. Import-light on purpose:
nothing here may import jax or the transport (both import *us*); the
fleet aggregator takes an already-connected NATS client by injection.
"""

from .aggregator import (
    Aggregator,
    SloEvaluator,
    SpanStore,
    assemble_trace,
    merge_expositions,
)
from .compile_cache import compile_cache_counts, install_compile_cache_listener
from .events import EVENTS, EventRing, emit
from .histogram import (
    HistSnapshot,
    LogHistogram,
    MergedHist,
    bucket_pairs,
    merge,
    quantile,
)
from .prom import PromRenderer
from .recorder import FlightRecorder
from .roofline import (
    DECODE_PROGRAMS,
    PREFILL_PROGRAMS,
    SPEC_PROGRAMS,
    WASTE_CATEGORIES,
    HbmLedger,
    RollingUtilization,
    chip_peaks,
    classify_program,
    dispatch_shape_key,
    efficiency_enabled,
    extract_dispatch_cost,
    resolve_chip_peaks,
)
from .trace import (
    STAGES,
    Span,
    Trace,
    new_span_id,
    new_trace_id,
    parse_span_context,
    span_context_value,
)

__all__ = [
    "Aggregator",
    "SloEvaluator",
    "SpanStore",
    "assemble_trace",
    "merge_expositions",
    "EVENTS",
    "EventRing",
    "emit",
    "FlightRecorder",
    "compile_cache_counts",
    "install_compile_cache_listener",
    "HistSnapshot",
    "LogHistogram",
    "MergedHist",
    "bucket_pairs",
    "merge",
    "quantile",
    "PromRenderer",
    "DECODE_PROGRAMS",
    "PREFILL_PROGRAMS",
    "SPEC_PROGRAMS",
    "WASTE_CATEGORIES",
    "HbmLedger",
    "RollingUtilization",
    "chip_peaks",
    "classify_program",
    "dispatch_shape_key",
    "efficiency_enabled",
    "extract_dispatch_cost",
    "resolve_chip_peaks",
    "STAGES",
    "Span",
    "Trace",
    "new_span_id",
    "new_trace_id",
    "parse_span_context",
    "span_context_value",
]

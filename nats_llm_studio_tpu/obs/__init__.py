"""Observability: trace context, bounded histograms, events, exposition.

The subsystem PR 1 threads through every layer — see histogram.py,
trace.py, events.py, prom.py. Import-light on purpose: nothing here may
import jax or the transport (both import *us*).
"""

from .compile_cache import compile_cache_counts, install_compile_cache_listener
from .events import EVENTS, EventRing, emit
from .histogram import HistSnapshot, LogHistogram
from .prom import PromRenderer
from .recorder import FlightRecorder
from .trace import STAGES, Trace, new_trace_id

__all__ = [
    "EVENTS",
    "EventRing",
    "emit",
    "FlightRecorder",
    "compile_cache_counts",
    "install_compile_cache_listener",
    "HistSnapshot",
    "LogHistogram",
    "PromRenderer",
    "STAGES",
    "Trace",
    "new_trace_id",
]

"""Bounded log-bucket histograms: O(1) record, O(buckets) snapshot.

Replaces the 16k-deque + full-sort percentile path in ``BatcherStats``
(PR 1): a fixed geometric bucket ladder covers [lo, hi] with a bounded
relative error per bucket (``growth`` - 1 worst case), so a long-lived
worker's latency percentiles cost a fixed few hundred ints of memory no
matter how many requests it has served. Snapshots are plain value
objects that subtract (``s1 - s0``) for per-phase deltas — the bench's
hand-rolled "remember the deque length" slicing becomes a snapshot diff
that cannot be invalidated by deque rotation.

Recording happens on the batcher owner thread while health/metrics
handlers snapshot from the asyncio thread, so both paths take the
histogram's lock (a handful of ns against a ~ms device dispatch).
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from dataclasses import dataclass


def _bounds(lo: float, hi: float, growth: float) -> tuple[float, ...]:
    if not (lo > 0 and hi > lo and growth > 1.0):
        raise ValueError(f"need 0 < lo < hi and growth > 1, got {lo}, {hi}, {growth}")
    out = [lo]
    b = lo
    while b < hi:
        b *= growth
        out.append(min(b, hi))
    return tuple(out)


# bucket ladders are shared across histogram instances (every batcher
# stat block holds five of these)
_BOUNDS_CACHE: dict[tuple[float, float, float], tuple[float, ...]] = {}


@dataclass(frozen=True)
class HistSnapshot:
    """Immutable point-in-time view; subtractable for phase deltas."""

    bounds: tuple[float, ...]  # upper edges; counts[i] holds v <= bounds[i]
    counts: tuple[int, ...]  # len(bounds) + 1: the last bucket is > bounds[-1]
    count: int
    total: float
    vmin: float | None  # None on empty snapshots and on deltas
    vmax: float | None

    def __sub__(self, other: "HistSnapshot") -> "HistSnapshot":
        if self.bounds != other.bounds:
            raise ValueError("cannot subtract snapshots with different bucket ladders")
        return HistSnapshot(
            bounds=self.bounds,
            counts=tuple(a - b for a, b in zip(self.counts, other.counts)),
            count=self.count - other.count,
            total=self.total - other.total,
            vmin=None,  # extrema are not recoverable for an interval
            vmax=None,
        )

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def percentile(self, q: float) -> float:
        """Estimate the q-quantile (0 <= q <= 1) by linear interpolation
        inside the containing bucket — same rank rule as sorting all
        recorded values ascending and indexing ``int(count * q)``."""
        if self.count <= 0:
            return 0.0
        rank = min(self.count - 1, int(self.count * q))
        cum = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if cum + c > rank:
                lo_edge = 0.0 if i == 0 else self.bounds[i - 1]
                hi_edge = self.bounds[i] if i < len(self.bounds) else (
                    self.vmax if self.vmax is not None else self.bounds[-1]
                )
                frac = (rank - cum + 1) / c
                est = lo_edge + (hi_edge - lo_edge) * frac
                # recorded extrema (when known) tighten the bucket edges
                if self.vmax is not None:
                    est = min(est, self.vmax)
                if self.vmin is not None:
                    est = max(est, self.vmin)
                return est
            cum += c
        return self.vmax if self.vmax is not None else self.bounds[-1]

    def to_dict(self) -> dict:
        return {
            "count": self.count,
            "sum": round(self.total, 3),
            "mean": round(self.mean, 3),
            "p50": round(self.percentile(0.5), 3),
            "p95": round(self.percentile(0.95), 3),
            "p99": round(self.percentile(0.99), 3),
            "max": round(self.vmax, 3) if self.vmax is not None else None,
        }


class LogHistogram:
    """Fixed-size thread-safe histogram over geometric bucket boundaries.

    ``record`` is O(log buckets) (one bisect + one increment under the
    lock); ``snapshot`` is O(buckets). Values below ``lo`` land in the
    first bucket, values above ``hi`` in the overflow bucket (percentile
    estimates there fall back to the recorded max).
    """

    __slots__ = ("bounds", "_counts", "_count", "_total", "_vmin", "_vmax", "_lock")

    def __init__(self, lo: float = 0.01, hi: float = 1e7, growth: float = 1.25):
        key = (lo, hi, growth)
        bounds = _BOUNDS_CACHE.get(key)
        if bounds is None:
            bounds = _BOUNDS_CACHE.setdefault(key, _bounds(lo, hi, growth))
        self.bounds = bounds
        self._counts = [0] * (len(bounds) + 1)
        self._count = 0
        self._total = 0.0
        self._vmin: float | None = None
        self._vmax: float | None = None
        self._lock = threading.Lock()

    def record(self, value: float) -> None:
        idx = bisect_left(self.bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self._count += 1
            self._total += value
            if self._vmin is None or value < self._vmin:
                self._vmin = value
            if self._vmax is None or value > self._vmax:
                self._vmax = value

    @property
    def count(self) -> int:
        return self._count

    @property
    def max(self) -> float:
        return self._vmax if self._vmax is not None else 0.0

    def snapshot(self) -> HistSnapshot:
        with self._lock:
            return HistSnapshot(
                bounds=self.bounds,
                counts=tuple(self._counts),
                count=self._count,
                total=self._total,
                vmin=self._vmin,
                vmax=self._vmax,
            )

    def percentile(self, q: float) -> float:
        return self.snapshot().percentile(q)


# ---------------------------------------------------------------------------
# Cross-worker histogram merging (promoted from bench.py's cluster/disagg
# phases so bench and the fleet aggregator share one tested code path).
# ---------------------------------------------------------------------------

_INF = float("inf")


def bucket_pairs(text: str, family: str) -> list[tuple[float, float]]:
    """Extract ``(upper_edge, cumulative_count)`` pairs for one histogram
    family from a Prometheus exposition text; ``+Inf`` maps to infinity."""
    pairs: list[tuple[float, float]] = []
    for line in text.splitlines():
        if not line.startswith(family + "_bucket"):
            continue
        i = line.index('le="') + 4
        le = line[i:line.index('"', i)]
        pairs.append((_INF if le == "+Inf" else float(le),
                      float(line.rsplit(None, 1)[1])))
    return pairs


@dataclass(frozen=True)
class MergedHist:
    """Delta-merged view over N workers' cumulative histogram buckets.

    Renderers elide empty buckets, so merging *cumulative* counts by edge
    across workers produces non-monotonic garbage; each series converts
    to per-bucket deltas first, then the deltas merge. Mean/variance use
    bucket midpoints (the +Inf bucket collapses to that series' last
    finite edge); quantiles return the upper bucket edge — resolution-
    honest, no interpolation.
    """

    # (midpoint, collapsed upper edge, count) — +Inf already collapsed
    samples: tuple[tuple[float, float, float], ...]
    # (true upper edge, count) with +Inf preserved, sorted — this is the
    # shape a renderer needs to re-expose the merged histogram
    deltas: tuple[tuple[float, float], ...]

    @property
    def count(self) -> float:
        return sum(n for _, _, n in self.samples)

    @property
    def mean(self) -> float:
        c = self.count
        return sum(v * n for v, _, n in self.samples) / c if c else 0.0

    @property
    def variance(self) -> float:
        c = self.count
        if not c:
            return 0.0
        m = self.mean
        return sum(n * (v - m) ** 2 for v, _, n in self.samples) / c

    @property
    def std(self) -> float:
        return self.variance ** 0.5

    def quantile(self, q: float) -> float:
        """First upper bucket edge whose cumulative merged delta count
        reaches ``q * count``; 0.0 on an empty merge. Always finite (the
        +Inf bucket was collapsed per-series at merge time)."""
        count = self.count
        if count <= 0:
            return 0.0
        agg: dict[float, float] = {}
        for _, upper, n in self.samples:
            agg[upper] = agg.get(upper, 0.0) + n
        cum = 0.0
        for edge, n in sorted(agg.items()):
            cum += n
            if cum >= q * count:
                return edge
        return 0.0

    def snapshot(self, total: float | None = None) -> HistSnapshot:
        """Rebuild a :class:`HistSnapshot` (for ``PromRenderer.histogram``)
        from the merged deltas. ``total`` should be the summed ``_sum`` of
        the source expositions; defaults to the midpoint estimate."""
        finite = [(e, n) for e, n in self.deltas if e != _INF]
        overflow = sum(n for e, n in self.deltas if e == _INF)
        counts = tuple(int(round(n)) for _, n in finite) + (int(round(overflow)),)
        if total is None:
            total = sum(v * n for v, _, n in self.samples)
        return HistSnapshot(
            bounds=tuple(e for e, _ in finite),
            counts=counts,
            count=int(round(sum(n for _, n in self.deltas))),
            total=total,
            vmin=None,
            vmax=None,
        )


def merge(series) -> MergedHist:
    """Merge an iterable of per-exposition cumulative bucket-pair lists
    (as returned by :func:`bucket_pairs`) into one :class:`MergedHist`.

    Per series, cumulative counts convert to deltas FIRST; negative
    deltas (counter resets, malformed input) are dropped rather than
    poisoning the merge.
    """
    samples: list[tuple[float, float, float]] = []
    true_deltas: dict[float, float] = {}
    for pairs in series:
        prev_edge, prev_cum = 0.0, 0.0
        for edge, cum in sorted(pairs):
            n = cum - prev_cum
            if n > 0:
                if edge == _INF:
                    mid_v = upper = prev_edge
                else:
                    mid_v = (prev_edge + edge) / 2
                    upper = edge
                samples.append((mid_v, upper, n))
                true_deltas[edge] = true_deltas.get(edge, 0.0) + n
            prev_cum = cum
            if edge != _INF:
                prev_edge = edge
    return MergedHist(samples=tuple(samples),
                      deltas=tuple(sorted(true_deltas.items())))


def quantile(pairs, q: float) -> float:
    """Resolution-honest quantile of a single exposition's cumulative
    bucket pairs — shorthand for ``merge([pairs]).quantile(q)``."""
    return merge([pairs]).quantile(q)

"""Request-scoped trace context: one id, one monotonic timestamp per stage.

A chat request's life is enqueue → admit dispatch → prefill → first token
→ decode → publish; the trace rides the request object through the worker
and the batcher owner thread, each layer stamping the stage it completes.
The report is returned in the response ``stats`` block, so one
``nats req lmstudio.chat_model`` shows the full latency waterfall with no
extra round-trip (and no clock-sync problem: every mark comes from the
same host's monotonic clock).

Marks are first-write-wins: a stage is stamped where it first completes,
and re-marking (e.g. a retry path crossing the same site) cannot move an
already-recorded timestamp backwards.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field

# canonical stage order for the waterfall; unknown stages append after
STAGES = ("recv", "enqueue", "admit", "prefill", "first_token", "decode_done", "publish")


def new_trace_id() -> str:
    return os.urandom(8).hex()


def new_span_id() -> str:
    return os.urandom(4).hex()


def span_context_value(trace_id: str, span_id: str) -> str:
    """Render a W3C traceparent-style header value (``00-<trace>-<span>-01``)
    carrying the caller's span as parent context for the next hop."""
    return f"00-{trace_id}-{span_id}-01"


def parse_span_context(value: str | None) -> tuple[str, str] | None:
    """Parse a traceparent-style value into ``(trace_id, span_id)``;
    anything malformed returns ``None`` rather than raising — a bad
    header must never fail the request it rode in on."""
    if not value or not isinstance(value, str):
        return None
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    _, trace_id, span_id, _ = parts
    if not trace_id or not span_id:
        return None
    return trace_id, span_id


@dataclass
class Span:
    """One hop of a cross-process trace, assembled fleet-side by trace_id.

    ``t0``/``t1`` are wall-clock seconds (``time.time()``) — unlike the
    in-process waterfall marks, spans cross host/process boundaries where
    monotonic clocks don't compare; the assembled tree orders children by
    ``t0`` and tolerates modest clock skew because causality comes from
    the parent links, not the timestamps.
    """

    trace_id: str
    span_id: str
    stage: str  # "gateway.request" | "router.attempt" | "worker.serve" | ...
    worker_id: str = ""
    parent_span_id: str = ""
    t0: float = 0.0
    t1: float = 0.0
    attrs: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        d = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "stage": self.stage,
            "worker_id": self.worker_id,
            "parent_span_id": self.parent_span_id,
            "t0": round(self.t0, 6),
            "t1": round(self.t1, 6),
        }
        if self.attrs:
            d["attrs"] = self.attrs
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Span | None":
        if not isinstance(d, dict):
            return None
        trace_id, span_id = d.get("trace_id"), d.get("span_id")
        if not isinstance(trace_id, str) or not isinstance(span_id, str):
            return None
        if not trace_id or not span_id:
            return None
        attrs = d.get("attrs")
        return cls(
            trace_id=trace_id,
            span_id=span_id,
            stage=str(d.get("stage", "")),
            worker_id=str(d.get("worker_id", "")),
            parent_span_id=str(d.get("parent_span_id", "")),
            t0=float(d.get("t0", 0.0) or 0.0),
            t1=float(d.get("t1", 0.0) or 0.0),
            attrs=attrs if isinstance(attrs, dict) else {},
        )


class Trace:
    __slots__ = ("trace_id", "attempt", "span_id", "parent_span_id",
                 "t0_wall", "_marks", "_lock")

    def __init__(self, trace_id: str | None = None, attempt: int | None = None,
                 parent_span_id: str = ""):
        self.trace_id = trace_id or new_trace_id()
        # retry attempt number (1-based) stamped from the X-Attempt
        # header: one trace id spans all attempts of a retried request,
        # so the attempt tag is what tells the spans apart
        self.attempt = attempt
        # every trace doubles as one span of the cross-process tree: the
        # hop that created it minted span_id, the upstream hop's span id
        # arrives in the Traceparent header as parent_span_id
        self.span_id = new_span_id()
        self.parent_span_id = parent_span_id
        self.t0_wall = time.time()
        self._marks: dict[str, float] = {}
        self._lock = threading.Lock()

    def to_span(self, stage: str, worker_id: str = "",
                attrs: dict | None = None) -> dict:
        """Close this trace's span now and return its wire dict."""
        return Span(
            trace_id=self.trace_id,
            span_id=self.span_id,
            stage=stage,
            worker_id=worker_id,
            parent_span_id=self.parent_span_id,
            t0=self.t0_wall,
            t1=time.time(),
            attrs=attrs or {},
        ).to_dict()

    def mark(self, stage: str, t: float | None = None) -> None:
        """Stamp ``stage`` at monotonic time ``t`` (now if omitted); the
        first mark for a stage wins. Safe from any thread — the worker's
        asyncio loop and the batcher owner thread stamp the same trace."""
        if t is None:
            t = time.monotonic()
        with self._lock:
            self._marks.setdefault(stage, t)

    def marks(self) -> dict[str, float]:
        with self._lock:
            return dict(self._marks)

    def report(self) -> dict:
        """``{trace_id, spans_ms, marks_ms}``: per-stage durations between
        consecutive *recorded* stages (absent stages are skipped, so a
        fake engine without batcher marks still reports queue → publish),
        plus each mark's offset from the first."""
        marks = self.marks()
        ordered = [(s, marks[s]) for s in STAGES if s in marks]
        ordered += sorted(
            ((s, t) for s, t in marks.items() if s not in STAGES), key=lambda x: x[1]
        )
        spans: dict[str, float] = {}
        offsets: dict[str, float] = {}
        if ordered:
            t0 = ordered[0][1]
            for stage, t in ordered:
                offsets[stage] = round(max(0.0, t - t0) * 1e3, 3)
            span_edges = {
                "queue_ms": ("enqueue", "admit"),
                "prefill_ms": ("admit", "prefill"),
                "first_token_ms": ("prefill", "first_token"),
                "decode_ms": ("first_token", "decode_done"),
                "publish_ms": ("decode_done", "publish"),
            }
            for name, (a, b) in span_edges.items():
                if a in marks and b in marks:
                    spans[name] = round(max(0.0, marks[b] - marks[a]) * 1e3, 3)
            spans["total_ms"] = round(max(0.0, ordered[-1][1] - t0) * 1e3, 3)
        out = {"trace_id": self.trace_id, "spans_ms": spans, "marks_ms": offsets}
        if self.attempt is not None:
            out["attempt"] = self.attempt
        # span linkage: lets a flight-recorder dump (which embeds this
        # report) be joined to the assembled cluster trace
        out["span_id"] = self.span_id
        if self.parent_span_id:
            out["parent_span_id"] = self.parent_span_id
        return out

"""Request-scoped trace context: one id, one monotonic timestamp per stage.

A chat request's life is enqueue → admit dispatch → prefill → first token
→ decode → publish; the trace rides the request object through the worker
and the batcher owner thread, each layer stamping the stage it completes.
The report is returned in the response ``stats`` block, so one
``nats req lmstudio.chat_model`` shows the full latency waterfall with no
extra round-trip (and no clock-sync problem: every mark comes from the
same host's monotonic clock).

Marks are first-write-wins: a stage is stamped where it first completes,
and re-marking (e.g. a retry path crossing the same site) cannot move an
already-recorded timestamp backwards.
"""

from __future__ import annotations

import os
import threading
import time

# canonical stage order for the waterfall; unknown stages append after
STAGES = ("recv", "enqueue", "admit", "prefill", "first_token", "decode_done", "publish")


def new_trace_id() -> str:
    return os.urandom(8).hex()


class Trace:
    __slots__ = ("trace_id", "attempt", "_marks", "_lock")

    def __init__(self, trace_id: str | None = None, attempt: int | None = None):
        self.trace_id = trace_id or new_trace_id()
        # retry attempt number (1-based) stamped from the X-Attempt
        # header: one trace id spans all attempts of a retried request,
        # so the attempt tag is what tells the spans apart
        self.attempt = attempt
        self._marks: dict[str, float] = {}
        self._lock = threading.Lock()

    def mark(self, stage: str, t: float | None = None) -> None:
        """Stamp ``stage`` at monotonic time ``t`` (now if omitted); the
        first mark for a stage wins. Safe from any thread — the worker's
        asyncio loop and the batcher owner thread stamp the same trace."""
        if t is None:
            t = time.monotonic()
        with self._lock:
            self._marks.setdefault(stage, t)

    def marks(self) -> dict[str, float]:
        with self._lock:
            return dict(self._marks)

    def report(self) -> dict:
        """``{trace_id, spans_ms, marks_ms}``: per-stage durations between
        consecutive *recorded* stages (absent stages are skipped, so a
        fake engine without batcher marks still reports queue → publish),
        plus each mark's offset from the first."""
        marks = self.marks()
        ordered = [(s, marks[s]) for s in STAGES if s in marks]
        ordered += sorted(
            ((s, t) for s, t in marks.items() if s not in STAGES), key=lambda x: x[1]
        )
        spans: dict[str, float] = {}
        offsets: dict[str, float] = {}
        if ordered:
            t0 = ordered[0][1]
            for stage, t in ordered:
                offsets[stage] = round(max(0.0, t - t0) * 1e3, 3)
            span_edges = {
                "queue_ms": ("enqueue", "admit"),
                "prefill_ms": ("admit", "prefill"),
                "first_token_ms": ("prefill", "first_token"),
                "decode_ms": ("first_token", "decode_done"),
                "publish_ms": ("decode_done", "publish"),
            }
            for name, (a, b) in span_edges.items():
                if a in marks and b in marks:
                    spans[name] = round(max(0.0, marks[b] - marks[a]) * 1e3, 3)
            spans["total_ms"] = round(max(0.0, ordered[-1][1] - t0) * 1e3, 3)
        out = {"trace_id": self.trace_id, "spans_ms": spans, "marks_ms": offsets}
        if self.attempt is not None:
            out["attempt"] = self.attempt
        return out

"""Fixed-capacity structured event ring for post-hoc incident diagnosis.

Aggregate counters say *that* requests were shed; the event ring says
*which* and *why* — the last N notable happenings (sheds, cancels, ring
compactions, engine load/evict, slow requests over a threshold) with
wall-clock timestamps, served on ``lmstudio.events``. Capacity-bounded:
emit is O(1), old events are overwritten, and the ``dropped`` counter
records how many fell off so a reader knows the window is partial.

Producers span threads (batcher owner, asyncio handlers, registry), so
every operation takes the ring's lock.
"""

from __future__ import annotations

import threading
import time


class EventRing:
    def __init__(self, capacity: int = 512):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._buf: list[dict | None] = [None] * capacity
        self._seq = 0  # total events ever emitted
        self._lock = threading.Lock()

    def emit(self, kind: str, **fields) -> dict:
        # both clocks: wall for humans/log-correlation, monotonic so
        # events line up with trace marks and recorder frames (which are
        # monotonic-stamped) without cross-clock arithmetic
        ev = {
            "kind": kind,
            "ts": round(time.time(), 3),
            "mono": round(time.monotonic(), 3),
            **fields,
        }
        with self._lock:
            ev["seq"] = self._seq
            self._buf[self._seq % self.capacity] = ev
            self._seq += 1
        return ev

    @property
    def emitted(self) -> int:
        return self._seq

    @property
    def dropped(self) -> int:
        """Events that have been overwritten (fell out of the window)."""
        return max(0, self._seq - self.capacity)

    def snapshot(self, kind: str | None = None, limit: int | None = None) -> list[dict]:
        """Events oldest-first, optionally filtered by ``kind`` and capped
        to the most recent ``limit``."""
        with self._lock:
            start = max(0, self._seq - self.capacity)
            out = [
                ev
                for i in range(start, self._seq)
                if (ev := self._buf[i % self.capacity]) is not None
            ]
        if kind is not None:
            out = [ev for ev in out if ev["kind"] == kind]
        if limit is not None and limit >= 0:
            out = out[-limit:]
        return out

    def clear(self) -> None:
        with self._lock:
            self._buf = [None] * self.capacity
            self._seq = 0


# process-wide default ring: the batcher owner thread, the registry, and
# the worker handlers all emit here; the worker serves it on
# ``lmstudio.events``
EVENTS = EventRing(512)


def emit(kind: str, **fields) -> dict:
    return EVENTS.emit(kind, **fields)

"""Environment-variable configuration.

The reference's config contract is README-only (env vars ``NATS_URL``,
``LMSTUDIO_BASE_URL``, ``LMSTUDIO_MODELS_DIR``, ``NATS_QUEUE_GROUP`` with
defaults — /root/reference/README.md:489-494, materialized into ``.env`` by
scripts/setup_unix.sh:111-115). This build keeps the same names and defaults,
drops ``LMSTUDIO_BASE_URL`` (no external HTTP engine exists any more), and
adds TPU-mesh settings.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from pathlib import Path


def _env(name: str, default: str) -> str:
    v = os.environ.get(name, "").strip()
    return v or default


@dataclass
class WorkerConfig:
    # reference-compatible contract (README.md:489-494)
    nats_url: str = field(default_factory=lambda: _env("NATS_URL", "nats://127.0.0.1:4222"))
    models_dir: Path = field(
        default_factory=lambda: Path(
            _env("LMSTUDIO_MODELS_DIR", str(Path.home() / ".lmstudio" / "models"))
        ).expanduser()
    )
    queue_group: str = field(default_factory=lambda: _env("NATS_QUEUE_GROUP", "lmstudio-workers"))
    subject_prefix: str = field(default_factory=lambda: _env("SUBJECT_PREFIX", "lmstudio"))

    # object store (README.md:250-318 pattern)
    bucket: str = field(default_factory=lambda: _env("MODEL_BUCKET", "llm-models"))

    # TPU build additions
    # serving mesh spec (parallel.mesh.serving_mesh): "auto" (default)
    # shards every local device on the tp axis — tensor-parallel serving
    # is the multi-device default; a single-device host serves unsharded.
    # "off"/"none"/"1" force tp=1; explicit specs like "tp=4",
    # "dp=2,tp=4", or the compact named-axis grammar "dp2,ep2,tp2" build
    # exactly that mesh. dp = independent batcher replicas (multiplied
    # slot capacity), ep = MoE expert sharding, sp = ring-attention
    # long-prompt prefill (RING_PREFILL_MIN_TOKENS). MESH_SHAPE is the
    # documented knob; TPU_MESH is honored as the legacy alias.
    mesh_shape: str = field(
        default_factory=lambda: _env("MESH_SHAPE", "") or _env("TPU_MESH", "auto")
    )
    # opt-in persistent XLA compilation cache (ROADMAP item 5, first
    # bite): a restarted worker (or an autoscaled replica on identical
    # hardware) replays compiles from disk instead of paying the
    # multi-second jit grid again. Empty = off. Applied by
    # ``configure_jax()`` at startup, before the first compile.
    compile_cache_dir: str = field(
        default_factory=lambda: _env("JAX_COMPILE_CACHE_DIR", "")
    )
    max_batch_slots: int = field(default_factory=lambda: int(_env("MAX_BATCH_SLOTS", "8")))
    max_seq_len: int = field(default_factory=lambda: int(_env("MAX_SEQ_LEN", "4096")))
    # weight-only quantization for serving: "none" (cfg dtype), "int8"
    # (per-output-channel — halves HBM weight traffic, fits 70B-class
    # models on a v5e-8) or "int4" (grouped asymmetric QTensor4,
    # WQUANT_GROUP rows per scale/zero-point — halves it again). WQUANT is
    # the documented knob; TPU_QUANT is honored as the legacy alias.
    quant_mode: str = field(
        default_factory=lambda: _env("WQUANT", "") or _env("TPU_QUANT", "none")
    )
    # rows of the contraction axis per int4 scale/zero-point pair (AWQ-style
    # grouping; degrades automatically when it does not divide the axis)
    wquant_group: int = field(default_factory=lambda: int(_env("WQUANT_GROUP", "32")))
    # "none" or "int8": quantized serving KV cache (ops/kvcache.py) — halves
    # decode cache traffic and per-slot HBM
    kv_quant_mode: str = field(default_factory=lambda: _env("TPU_KV_QUANT", "none"))
    # comma-separated URL schemes pull_model may fetch directly; https-only
    # by default on serving workers (bus clients must not be able to SSRF
    # through the worker or read its local files). Empty string disables.
    url_pull_schemes: str = field(default_factory=lambda: _env("URL_PULL_SCHEMES", "https"))
    # ceiling on a single pull_model URL download (disk-fill guard); default
    # mirrors the reference's 100 GiB JetStream file store (setup_unix.sh:93)
    max_url_pull_bytes: int = field(
        default_factory=lambda: int(_env("MAX_URL_PULL_BYTES", str(100 << 30)))
    )
    # overload bounds on the batcher admit queue (0 disables either).
    # Depth: chat_model sheds immediately past this many queued requests.
    # Age: waiters older than this are shed at admit time. Shedding replies
    # with an honest error envelope so queue-group peers absorb the overflow
    # (/root/reference/README.md:478-484); without bounds the r4 bench
    # measured 38.6 s of silent queueing. Unset ADMIT_QUEUE_LIMIT derives
    # 4 x MAX_BATCH_SLOTS; an explicit 0 disables the depth bound.
    admit_queue_limit: int = field(
        default_factory=lambda: int(_env("ADMIT_QUEUE_LIMIT", "-1"))
    )
    admit_max_age_ms: float = field(
        default_factory=lambda: float(_env("ADMIT_MAX_AGE_MS", "30000"))
    )
    # automatic prefix KV cache (serve/prefix_cache.py): per-engine budget
    # in prefill-chunk blocks, priced against the HBM admission budget.
    # PREFIX_CACHE=0 is the hard off-switch (wins over PREFIX_CACHE_BLOCKS);
    # PREFIX_CACHE_BLOCKS=0 also disables.
    prefix_cache_blocks: int = field(
        default_factory=lambda: int(_env("PREFIX_CACHE_BLOCKS", "64"))
    )
    # paged KV (serve/block_pool.py): ONE refcounted fixed-size-block pool
    # shared by live slots, the prefix cache, and spec decode, addressed
    # through per-slot block tables. Default on; KV_PAGED=0/false/off
    # restores the pre-paged contiguous per-slot rings (the bit-equivalence
    # baseline). KV_BLOCK_TOKENS is tokens per block (snapped down to
    # divide the prefill chunk); KV_POOL_BLOCKS=0 auto-sizes for zero
    # starvation (every slot at max_seq + the prefix budget) — deployments
    # under-provision it to pack more slots into the same HBM.
    kv_paged: bool = field(
        default_factory=lambda: _env("KV_PAGED", "1").strip().lower()
        not in ("0", "false", "off")
    )
    kv_block_tokens: int = field(
        default_factory=lambda: int(_env("KV_BLOCK_TOKENS", "16"))
    )
    kv_pool_blocks: int = field(
        default_factory=lambda: int(_env("KV_POOL_BLOCKS", "0"))
    )
    # -- hierarchical KV tiers (serve/kv_tiers.py) ---------------------------
    # host-RAM tier budget in bytes under the HBM block pool: evicted/
    # reclaimed prefix-cache chunks demote here (and spill onward to the
    # Object Store) instead of being dropped. 0 disables tiering entirely.
    kv_host_pool_bytes: int = field(
        default_factory=lambda: int(_env("KV_HOST_POOL_BYTES", str(256 << 20)))
    )
    # spill host-tier evictions to the JetStream Object Store as KVX1 blobs
    # (bucket "kv-tier"); the cold tier survives process death, so a
    # respawned worker warm-imports its hottest prefixes with no live donor
    kv_spill_objstore: bool = field(
        default_factory=lambda: _env("KV_SPILL_OBJSTORE", "1").strip().lower()
        not in ("0", "false", "off")
    )
    # slot suspend/resume (swap-don't-shed): under pool exhaustion or
    # SHED_ONLY brownout, demote a victim slot's KV + resume state to host
    # RAM and continue it later bit-identically instead of shedding/
    # cancelling. KV_SUSPEND=0 is the kill switch (pre-tier shed behavior).
    kv_suspend: bool = field(
        default_factory=lambda: _env("KV_SUSPEND", "1").strip().lower()
        not in ("0", "false", "off")
    )
    # proactive demotion low-water mark: each owner tick with the pool's
    # free fraction below this, cold cache chunks demote to the host tier
    # ahead of demand (admission then allocates without synchronous swaps)
    kv_demote_free_frac: float = field(
        default_factory=lambda: float(_env("KV_DEMOTE_FREE_FRAC", "0.10"))
    )
    # promotion-on-hit ceiling: at most this many tiered chunks re-enter the
    # pool per admit (bounds the synchronous device_put burst a deep
    # host-tier hit can inject ahead of one prefill)
    kv_promote_chunks: int = field(
        default_factory=lambda: int(_env("KV_PROMOTE_CHUNKS", "64"))
    )
    # cold-tier object-count cap; shallowest chains purge first
    kv_spill_max_objects: int = field(
        default_factory=lambda: int(_env("KV_SPILL_MAX_OBJECTS", "512"))
    )
    # speculative decoding (serve/spec.py): max prompt-lookup draft tokens
    # per slot per verify dispatch. SPEC_DECODE=0 is the hard off-switch
    # (wins over SPEC_DECODE_K); SPEC_DECODE_K=0 also disables. NOTE: k > 0
    # runs the engine cache in positional layout (per-row scatter writes),
    # trading some high-occupancy ring throughput for the low-occupancy
    # speculative win — throughput-tuned high-batch deployments should set
    # SPEC_DECODE=0.
    spec_decode_k: int = field(
        default_factory=lambda: int(_env("SPEC_DECODE_K", "6"))
    )
    # verify dispatches pause above this many live slots (decode turns
    # compute-bound and drafts stop paying); plain decode continues
    spec_max_active: int = field(
        default_factory=lambda: int(_env("SPEC_DECODE_MAX_ACTIVE", "4"))
    )
    # -- transport resilience (transport/client.py) --------------------------
    # reconnect attempts after a lost connection (exp backoff + jitter,
    # base→cap below); 0 disables auto-reconnect (connection loss closes the
    # client, pre-resilience behavior)
    max_reconnects: int = field(
        default_factory=lambda: int(_env("NATS_MAX_RECONNECTS", "60"))
    )
    reconnect_wait_s: float = field(
        default_factory=lambda: float(_env("NATS_RECONNECT_WAIT_S", "0.05"))
    )
    reconnect_max_wait_s: float = field(
        default_factory=lambda: float(_env("NATS_RECONNECT_MAX_WAIT_S", "2.0"))
    )
    # client-originated PING keepalive: a connection that misses two
    # consecutive PONGs is declared stale and dropped into the reconnect
    # path. 0 disables the keepalive task.
    ping_interval_s: float = field(
        default_factory=lambda: float(_env("NATS_PING_INTERVAL_S", "30"))
    )
    # -- engine supervision (serve/worker.py + serve/registry.py) ------------
    # watchdog poll period over loaded batchers; 0 disables supervision
    supervise_interval_s: float = field(
        default_factory=lambda: float(_env("SUPERVISE_INTERVAL_S", "2"))
    )
    # a NON-idle batcher whose owner loop hasn't stamped its heartbeat for
    # this long is declared hung and restarted; generous default because a
    # cold XLA compile of a big prefill program legitimately stalls the
    # loop for minutes. 0 disables the hang check (crash detection stays).
    engine_heartbeat_timeout_s: float = field(
        default_factory=lambda: float(_env("ENGINE_HEARTBEAT_TIMEOUT_S", "120"))
    )
    engine_restart_backoff_s: float = field(
        default_factory=lambda: float(_env("ENGINE_RESTART_BACKOFF_S", "0.5"))
    )
    engine_restart_backoff_max_s: float = field(
        default_factory=lambda: float(_env("ENGINE_RESTART_BACKOFF_MAX_S", "30"))
    )
    # more than this many crashes inside the window poisons the model:
    # get_engine refuses (retryable envelope) until a delete/pull resets it
    engine_max_restarts: int = field(
        default_factory=lambda: int(_env("ENGINE_MAX_RESTARTS", "3"))
    )
    engine_restart_window_s: float = field(
        default_factory=lambda: float(_env("ENGINE_RESTART_WINDOW_S", "120"))
    )
    # -- overload robustness (serve/brownout.py + serve/batcher.py) ----------
    # end-to-end deadline propagation: request()/request_stream() stamp the
    # caller's budget as X-Deadline-Ms; the worker converts it to a monotonic
    # deadline (capped by chat_timeout_s) so the batcher can shed expired
    # requests before prefill and abort mid-decode slots whose caller gave
    # up. DEADLINE_PROPAGATION=0/false/off disables the worker-side half
    # (clients still stamp the cheap header). DEADLINE_MIN_TOKENS and the
    # BROWNOUT_* thresholds parse in serve/registry.py.
    deadline_propagation: bool = field(
        default_factory=lambda: _env("DEADLINE_PROPAGATION", "1").strip().lower()
        not in ("0", "false", "off")
    )
    # adaptive brownout controller: NORMAL → BROWNOUT → SHED_ONLY with
    # hysteresis on queue depth, queue age p95, and HBM headroom.
    # BROWNOUT=0/false/off disables (batcher falls back to the binary
    # depth/age sheds only).
    brownout: bool = field(
        default_factory=lambda: _env("BROWNOUT", "1").strip().lower()
        not in ("0", "false", "off")
    )
    # -- flight recorder + debug subjects (obs/recorder.py) ------------------
    # bounded ring of periodic batcher state frames, sampled by the owner
    # loop; anomaly-triggered dumps (engine restart, pool exhaustion,
    # SHED_ONLY entry, slow requests) write frames + event tail + trace to
    # OBS_DUMP_DIR. OBS_RECORDER=0 disables sampling and dumps entirely;
    # an empty OBS_DUMP_DIR keeps the in-memory ring (debug.snapshot still
    # serves it) but writes nothing to disk.
    obs_recorder: bool = field(
        default_factory=lambda: _env("OBS_RECORDER", "1").strip().lower()
        not in ("0", "false", "off")
    )
    obs_recorder_interval_ms: float = field(
        default_factory=lambda: float(_env("OBS_RECORDER_INTERVAL_MS", "250"))
    )
    obs_dump_dir: str = field(default_factory=lambda: _env("OBS_DUMP_DIR", "").strip())
    # deep-introspection subjects (lmstudio.debug.snapshot / .dump): off by
    # default — they expose slot tables and can force disk writes, so only
    # operators who opt in get them on the bus
    debug_subjects: bool = field(
        default_factory=lambda: _env("DEBUG_SUBJECTS", "0").strip().lower()
        in ("1", "true", "on")
    )
    # -- cluster membership + failover routing (serve/router.py) -------------
    # stable cluster identity: stamped on every reply (X-Worker-Id), in
    # adverts, prom labels, recorder frames, and the CONNECT name
    # (tpu-worker-<id> — the chaos harness's worker-scoped kill switch keys
    # on it). Empty WORKER_ID derives a short random id at startup.
    worker_id: str = field(default_factory=lambda: _env("WORKER_ID", ""))
    # period between lmstudio.cluster.adverts publishes; 0 disables the
    # advert loop (single-worker deployments lose nothing)
    cluster_advert_interval_s: float = field(
        default_factory=lambda: float(_env("CLUSTER_ADVERT_INTERVAL_S", "1.0"))
    )
    # graceful drain (lmstudio.admin.drain): in-flight decode gets this long
    # to finish after the queue subs are dropped; the remainder is failed
    # with the retryable draining envelope so peers absorb it
    drain_deadline_s: float = field(
        default_factory=lambda: float(_env("DRAIN_DEADLINE_S", "30"))
    )
    # router: an advert older than this marks the worker dead (dropped from
    # steering). Must comfortably exceed the advert interval.
    router_stale_after_s: float = field(
        default_factory=lambda: float(_env("ROUTER_STALE_AFTER_S", "5.0"))
    )
    # router: prompt-head chars hashed for prefix-cache locality steering
    # (0 disables locality; load-only steering remains)
    router_prefix_head_chars: int = field(
        default_factory=lambda: int(_env("ROUTER_PREFIX_HEAD_CHARS", "256"))
    )
    # -- disaggregated prefill/decode serving (serve/worker.py + router.py) ---
    # phase role for this worker: "" (monolithic, the default — prefill and
    # decode share the batcher), "prefill" (runs chunked prefill and exports
    # finished KV blocks over lmstudio.worker.<id>.kv_export; the role-aware
    # router never steers chats at it, though it stays in the queue group as
    # a degradation backstop), or "decode" (pulls exported blocks from its
    # paired prefill worker before serving, so the slot starts decoding with
    # zero prefill work; any transfer failure falls back to local prefill)
    worker_role: str = field(default_factory=lambda: _env("WORKER_ROLE", "").strip().lower())
    # wall budget for one KV transfer (the decode worker's pull of the
    # prefill worker's exported blocks); a timeout falls back to local
    # prefill and counts into lmstudio_kv_transfer_failures_total
    kv_transfer_timeout_s: float = field(
        default_factory=lambda: float(_env("KV_TRANSFER_TIMEOUT_S", "10"))
    )
    # per-message chunk size for direct NATS block transfers (must stay
    # under the broker max_payload; 256 KiB leaves generous header room)
    kv_transfer_chunk_bytes: int = field(
        default_factory=lambda: int(_env("KV_TRANSFER_CHUNK_BYTES", str(256 << 10)))
    )
    # blobs at or above this size ship via the JetStream Object Store
    # (one put + an object ref over the bus) instead of chunked publishes;
    # 0 disables the object-store path entirely (always chunked publishes)
    kv_transfer_objstore_bytes: int = field(
        default_factory=lambda: int(_env("KV_TRANSFER_OBJSTORE_BYTES", str(8 << 20)))
    )
    # -- OpenAI-compatible HTTP/SSE gateway (gateway/server.py) ---------------
    # bind address for ``python -m nats_llm_studio_tpu gateway``; loopback by
    # default — exposing the front door beyond the host is an explicit choice
    gateway_host: str = field(default_factory=lambda: _env("GATEWAY_HOST", "127.0.0.1"))
    gateway_port: int = field(default_factory=lambda: int(_env("GATEWAY_PORT", "8080")))
    # concurrent HTTP connections admitted before 503 (streaming responses
    # hold a connection for their whole decode, so this bounds gateway RAM
    # and protects the bus from connection storms)
    gateway_max_conn: int = field(
        default_factory=lambda: int(_env("GATEWAY_MAX_CONN", "256"))
    )
    # -- multi-tenant QoS (serve/qos.py, gateway auth + batcher fair share) ---
    # API-key table: comma-separated ``key:tenant:class[:weight[:rps
    # [:monthly_tokens]]]`` entries (class in batch|standard|premium; rps is
    # a per-key token-bucket rate, monthly_tokens a per-tenant completion
    # quota; 0/omitted = unlimited). Empty (the default) disables auth: the
    # gateway serves everyone as the anonymous standard tenant, exactly the
    # pre-QoS behavior.
    api_keys: str = field(default_factory=lambda: _env("API_KEYS", ""))
    # tenant-label cardinality cap for every Prometheus exposition (worker,
    # gateway, aggregator): the top-K tenants by volume keep their own rows,
    # the rest roll up into tenant="other" — a key-guessing client cannot
    # mint unbounded label values. 0 disables the cap.
    qos_tenant_topk: int = field(
        default_factory=lambda: int(_env("QOS_TENANT_TOPK", "8"))
    )
    # premium preempt-to-host-tier: a premium admit that finds the KV pool
    # full suspends the lowest-class victim slot to host RAM (resumed
    # bit-identically when pressure clears) before ever shedding. Off
    # restores class-blind victim selection (largest slot first).
    qos_preempt: bool = field(
        default_factory=lambda: _env("QOS_PREEMPT", "1").strip().lower()
        not in ("0", "false", "off")
    )
    # deficit-round-robin quantum in prompt tokens per round per unit of
    # class weight: smaller = tighter interleaving (fairness converges
    # faster), larger = longer per-tenant runs (better admit batching)
    qos_quantum_tokens: int = field(
        default_factory=lambda: int(_env("QOS_QUANTUM_TOKENS", "256"))
    )
    # -- cluster observability plane (obs/aggregator.py + obs/trace.py) -------
    # kill switch for cross-process span emission: when off, gateway/router/
    # worker skip publishing span batches to {prefix}.obs.spans entirely
    # (Traceparent headers still flow — they cost nothing)
    obs_spans: bool = field(
        default_factory=lambda: _env("OBS_SPANS", "1").strip().lower()
        not in ("0", "false", "off")
    )
    # fleet aggregator scrape cadence: how often the collector requests each
    # live worker's directed metrics.prom subject
    obs_scrape_interval_s: float = field(
        default_factory=lambda: float(_env("OBS_SCRAPE_INTERVAL_S", "2.0"))
    )
    # embed the fleet aggregator inside ``python -m nats_llm_studio_tpu
    # route`` (one fewer process for small clusters); the standalone
    # ``... obs`` subcommand ignores this knob and always runs one
    obs_aggregator: bool = field(
        default_factory=lambda: _env("OBS_AGGREGATOR", "0").strip().lower()
        in ("1", "true", "on")
    )
    # SLO objectives evaluated by the aggregator over fast/slow burn windows:
    # cluster TTFT p95 target (ms), slow window length (s; the fast window is
    # window/12 clamped to at least two scrape intervals), minimum
    # served-or-retryable ratio, and maximum shed rate
    slo_ttft_p95_ms: float = field(
        default_factory=lambda: float(_env("SLO_TTFT_P95_MS", "2000"))
    )
    slo_window_s: float = field(
        default_factory=lambda: float(_env("SLO_WINDOW_S", "60"))
    )
    slo_served_ratio: float = field(
        default_factory=lambda: float(_env("SLO_SERVED_RATIO", "0.99"))
    )
    slo_shed_ratio: float = field(
        default_factory=lambda: float(_env("SLO_SHED_RATIO", "0.05"))
    )
    # -- elastic autoscaling (serve/autoscaler.py, ISSUE 15) ------------------
    # embed the autoscaler inside ``route``/``obs`` (the standalone
    # ``... autoscale`` subcommand always runs one); spawns/drains local
    # worker subprocesses against the advert + SLO-burn signals
    obs_autoscale: bool = field(
        default_factory=lambda: _env("OBS_AUTOSCALE", "0").strip().lower()
        in ("1", "true", "on")
    )
    # fleet bounds: never drain below min, never spawn past max
    autoscale_min_workers: int = field(
        default_factory=lambda: int(_env("AUTOSCALE_MIN", "1"))
    )
    autoscale_max_workers: int = field(
        default_factory=lambda: int(_env("AUTOSCALE_MAX", "4"))
    )
    # control-loop cadence and hysteresis: pressure (SLO burn, deep queues,
    # brownout) must persist up_dwell before a spawn; calm must persist
    # down_dwell before a drain; cooldown blocks back-to-back actions
    autoscale_interval_s: float = field(
        default_factory=lambda: float(_env("AUTOSCALE_INTERVAL_S", "1.0"))
    )
    autoscale_up_dwell_s: float = field(
        default_factory=lambda: float(_env("AUTOSCALE_UP_DWELL_S", "2.0"))
    )
    autoscale_down_dwell_s: float = field(
        default_factory=lambda: float(_env("AUTOSCALE_DOWN_DWELL_S", "15.0"))
    )
    autoscale_cooldown_s: float = field(
        default_factory=lambda: float(_env("AUTOSCALE_COOLDOWN_S", "5.0"))
    )
    # queue-depth thresholds: mean advert depth at/above up_queue_depth is
    # pressure; total fleet depth at/below down_queue_depth is idle
    autoscale_up_queue_depth: float = field(
        default_factory=lambda: float(_env("AUTOSCALE_UP_QUEUE_DEPTH", "8"))
    )
    autoscale_down_queue_depth: float = field(
        default_factory=lambda: float(_env("AUTOSCALE_DOWN_QUEUE_DEPTH", "1"))
    )
    # spawn supervision: a spawned worker must advertise within grace_s or
    # it counts as a spawn failure; breaker_failures consecutive failures
    # open the circuit breaker for breaker_cooldown_s (no spawn storms)
    autoscale_spawn_grace_s: float = field(
        default_factory=lambda: float(_env("AUTOSCALE_SPAWN_GRACE_S", "20"))
    )
    autoscale_breaker_failures: int = field(
        default_factory=lambda: int(_env("AUTOSCALE_BREAKER_FAILURES", "3"))
    )
    autoscale_breaker_cooldown_s: float = field(
        default_factory=lambda: float(_env("AUTOSCALE_BREAKER_COOLDOWN_S", "30"))
    )
    # hottest prefix-cache paths pushed to a replacement at drain/scale-up
    # (warm handoff); 0 disables handoff entirely
    autoscale_handoff_prefixes: int = field(
        default_factory=lambda: int(_env("AUTOSCALE_HANDOFF_PREFIXES", "4"))
    )

    def __post_init__(self) -> None:
        if self.admit_queue_limit < 0:  # unset: scale with the slot count
            self.admit_queue_limit = 4 * self.max_batch_slots
        if _env("PREFIX_CACHE", "").strip().lower() in ("0", "false", "off"):
            self.prefix_cache_blocks = 0
        if _env("SPEC_DECODE", "").strip().lower() in ("0", "false", "off"):
            self.spec_decode_k = 0
        if not self.worker_id:
            from .utils import next_nuid

            self.worker_id = f"w-{next_nuid()[-8:].lower()}"
        if self.worker_role not in ("", "prefill", "decode"):
            raise ValueError(
                f"WORKER_ROLE must be '', 'prefill' or 'decode', "
                f"got {self.worker_role!r}"
            )

    def configure_jax(self) -> None:
        """Apply process-wide JAX settings. Must run before the first
        compile (main.py calls it ahead of mesh construction); idempotent,
        and a no-op when no knob is set — library users who never call it
        lose nothing but the compile cache."""
        if not self.compile_cache_dir:
            return
        import jax

        jax.config.update("jax_compilation_cache_dir", self.compile_cache_dir)
        try:
            # the serving grid is many sub-second programs (per-bucket
            # prefills, per-window chunks); cache all of them, not just
            # the slow ones, so a supervisor bounce replays the whole grid
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        except AttributeError:  # older jax: keep the directory, lose the knob
            pass

    # timeout ladder — mirrors the reference's per-op deadlines
    # (nats_llm_studio.go:229, :251, :289, :328)
    list_timeout_s: float = 30.0
    pull_timeout_s: float = 600.0
    delete_timeout_s: float = 120.0
    chat_timeout_s: float = 120.0

    def subject(self, op: str) -> str:
        return f"{self.subject_prefix}.{op}"
